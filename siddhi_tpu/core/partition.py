"""Partitions: per-key isolated query state.

Reference: core/partition/PartitionRuntime.java:68-370 — `partition with (expr
of Stream) begin ... end` lazily clones the whole inner query graph per key
value (:256-315) and routes events into per-key local junctions; range
partitions pick the first matching condition (executor/RangePartitionExecutor).

TPU-native design: instead of cloned object graphs, the inner query's carried
state gets a leading partition axis [P] and the step is `jax.vmap`ed over it —
one compiled program, every partition's windows/aggregators advancing in
parallel on device (SURVEY §2.7: partition -> vmap/segment over the key
dimension). A shared key->slot table (same machinery as group-by) maps key
values to partition slots; `#inner` streams stay [P]-shaped between inner
queries, never flattening until output leaves the partition.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.event import (
    EventBatch,
    KIND_CURRENT,
    KIND_TIMER,
    StreamSchema,
)
from siddhi_tpu.core.executor import Env, Scope, TS_ATTR, compile_expression
from siddhi_tpu.core.join import JoinQueryRuntime
from siddhi_tpu.core.query_runtime import QueryRuntime
from siddhi_tpu.core.types import AttrType
from siddhi_tpu.ops.group import assign_slots
from siddhi_tpu.query_api.execution import (
    InsertIntoStream,
    Partition,
    Query,
    RangePartitionType,
    SingleInputStream,
    ValuePartitionType,
)

DEFAULT_PARTITIONS = 32
NO_TIMER = jnp.iinfo(jnp.int64).max


def _tile(x, p):
    return jnp.repeat(x[None], p, axis=0)


def _reduce_paux(auxs: dict, povf=None) -> dict:
    """Collapse vmapped per-partition aux values: timers take the earliest,
    boolean flags OR together; the key-table overflow folds in."""
    aux = {
        k: (v.min() if k == "next_timer" else v.any()) for k, v in auxs.items()
    }
    if povf is not None:
        aux["partition_overflow"] = aux.get(
            "partition_overflow", np.bool_(False)
        ) | povf
    return aux


class PartitionedQueryRuntime(QueryRuntime):
    """One inner query with a leading [P] partition axis on its state.

    `key_of(env) -> (keys [B] int64, matched [B] bool)` routes outer-stream
    batches; None means the input is an `#inner` stream whose batches arrive
    already [P]-shaped.
    """

    def __init__(
        self,
        query: Query,
        query_id: str,
        in_schema: StreamSchema,
        interner,
        p_capacity: int,
        key_of: Optional[Callable],
        group_capacity=None,
    ):
        super().__init__(
            query, query_id, in_schema, interner,
            group_capacity=group_capacity, tables={},
        )
        self.p = int(p_capacity)
        # the DECLARED capacity: parallel/shard.py may pad self.p up to a
        # multiple of the mesh size with dead lanes; the shared ptable (and
        # so assign_slots' overflow threshold) stays at p_logical
        self.p_logical = self.p
        self.key_of = key_of
        self.inner_publish = None  # set when inserting into an #inner stream
        self._pstep_outer = jax.jit(self._pstep_outer_impl, donate_argnums=(1,))
        self._pstep_inner = jax.jit(self._pstep_inner_impl, donate_argnums=(0,))

    def init_state(self):
        one = super().init_state()
        return jax.tree_util.tree_map(lambda x: _tile(x, self.p), one)

    # ---- device ------------------------------------------------------------

    def _vmapped(self, states, make_valid, batch: EventBatch, now):
        def one(state, p):
            b2 = EventBatch(batch.ts, batch.kind, make_valid(p), batch.cols)
            st, _ts, out, aux = self._step_impl(state, {}, b2, now)
            return st, out, aux

        states2, outs, auxs = jax.vmap(one)(states, jnp.arange(self.p))
        return states2, outs, _reduce_paux(auxs)

    def _pstep_outer_impl(self, ptable, states, batch: EventBatch, now):
        cols = {(self.ref, None, n): c for n, c in batch.cols.items()}
        cols[(self.ref, None, TS_ATTR)] = batch.ts
        env = Env(cols, now=now)
        keys, matched = self.key_of(env)
        active = batch.valid & (batch.kind == KIND_CURRENT) & matched
        pk, pu, pn, slot, _grp, povf = assign_slots(
            ptable["keys"], ptable["used"], ptable["n"], keys, active
        )
        # overflow remap: assign_slots' dead slot equals the ptable
        # capacity (= p_logical); when the [P] axis is padded for mesh
        # divisibility that index is a real (dead) lane, so push overflow
        # past every lane
        slot = jnp.where(slot >= self.p_logical, jnp.int32(self.p), slot)
        is_timer = batch.valid & (batch.kind == KIND_TIMER)

        def make_valid(p):
            return (active & (slot == p)) | is_timer

        states2, outs, aux = self._vmapped(states, make_valid, batch, now)
        aux["partition_overflow"] = aux.get(
            "partition_overflow", np.bool_(False)
        ) | povf
        return {"keys": pk, "used": pu, "n": pn}, states2, outs, aux

    def _pstep_inner_impl(self, states, pbatch, now):
        """pbatch: EventBatch with leading [P] axis on every lane."""
        def one(state, b2):
            st, _ts, out, aux = self._step_impl(state, {}, b2, now)
            return st, out, aux

        states2, outs, auxs = jax.vmap(one)(states, pbatch)
        return states2, outs, _reduce_paux(auxs)

    # ---- host ----------------------------------------------------------------

    def receive_partitioned(self, ptable, batch: EventBatch, now: int):
        """Outer-stream arrival. Returns (ptable', flat_out, p_out, aux)."""
        with self._receive_lock:
            if self.state is None:
                self.state = self._fresh(self.init_state())
            ptable, self.state, outs, aux = self._pstep_outer(
                ptable, self.state, batch, jnp.asarray(now, jnp.int64)
            )
        self._warn_aux(aux)
        return ptable, _flatten(outs), outs, aux

    def receive_inner(self, pbatch, now: int):
        with self._receive_lock:
            if self.state is None:
                self.state = self._fresh(self.init_state())
            self.state, outs, aux = self._pstep_inner(
                self.state, pbatch, jnp.asarray(now, jnp.int64)
            )
        self._warn_aux(aux)
        return _flatten(outs), outs, aux


class PartitionedJoinQueryRuntime(JoinQueryRuntime):
    """A join whose per-side state carries a leading [P] partition axis —
    both sides' events route to their key's partition and probe only that
    partition's windows (reference: per-key cloned JoinStreamRuntimes,
    PartitionTestCase join coverage)."""

    def __init__(
        self,
        query: Query,
        query_id: str,
        left_schema: StreamSchema,
        right_schema: StreamSchema,
        interner,
        p_capacity: int,
        key_of_by_side: dict,  # side -> key fn
        group_capacity=None,
        join_capacity: int = 512,
    ):
        super().__init__(
            query, query_id, left_schema, right_schema, interner,
            group_capacity=group_capacity, join_capacity=join_capacity,
            tables={},
        )
        if self.needs_scheduler["l"] or self.needs_scheduler["r"]:
            raise SiddhiAppCreationError(
                "time windows on join sides inside partitions are not "
                "supported yet"
            )
        self.p = int(p_capacity)
        self.key_of_by_side = key_of_by_side
        self._psteps = {
            side: jax.jit(
                lambda pt, st, b, now, _s=side: self._pstep_impl(pt, st, b, now, _s),
                donate_argnums=(1,),
            )
            for side in ("l", "r")
        }

    def init_state(self):
        one = super().init_state()
        return jax.tree_util.tree_map(lambda x: _tile(x, self.p), one)

    def _pstep_impl(self, ptable, states, batch: EventBatch, now, side: str):
        sid = (self.join.left if side == "l" else self.join.right).stream_id
        cols = {(sid, None, n): c for n, c in batch.cols.items()}
        cols[(sid, None, TS_ATTR)] = batch.ts
        keys, matched = self.key_of_by_side[side](Env(cols, now=now))
        active = batch.valid & (batch.kind == KIND_CURRENT) & matched
        pk, pu, pn, slot, _grp, povf = assign_slots(
            ptable["keys"], ptable["used"], ptable["n"], keys, active
        )
        is_timer = batch.valid & (batch.kind == KIND_TIMER)

        def one(state, p):
            sub_valid = (active & (slot == p)) | is_timer
            b2 = EventBatch(batch.ts, batch.kind, sub_valid, batch.cols)
            st, _ts, out, aux = self._step_impl(state, {}, b2, now, side)
            return st, out, aux

        states2, outs, auxs = jax.vmap(one)(states, jnp.arange(self.p))
        aux = _reduce_paux(auxs, povf)
        return {"keys": pk, "used": pu, "n": pn}, states2, outs, aux

    def receive_partitioned(self, ptable, batch: EventBatch, now: int, side: str):
        with self._receive_lock:
            if self.state is None:
                self.state = self._fresh(self.init_state())
            ptable, self.state, outs, aux = self._psteps[side](
                ptable, self.state, batch, jnp.asarray(now, jnp.int64)
            )
        self._warn_aux(aux)
        return ptable, _flatten(outs), outs, aux


class PartitionedPatternQueryRuntime:
    """A pattern/sequence whose token table carries a leading [P] axis —
    each key value runs an independent NFA (reference: per-key cloned
    state runtimes, PartitionTestCase pattern/sequence coverage)."""

    def __init__(
        self,
        query: Query,
        query_id: str,
        schemas: dict,
        interner,
        p_capacity: int,
        key_fns: dict,  # stream_id -> key fn
        group_capacity=None,
        token_capacity: int = 128,
        count_capacity: int = 8,
        batch_size: int = 64,
    ):
        from siddhi_tpu.core.pattern_runtime import PatternQueryRuntime

        self._inner = PatternQueryRuntime(
            query, query_id, schemas, interner,
            group_capacity=group_capacity, token_capacity=token_capacity,
            count_capacity=count_capacity, batch_size=batch_size, tables={},
        )
        inner = self._inner
        self.query = query
        self.query_id = query_id
        self.prog = inner.prog
        self.out_schema = inner.out_schema
        self.output_events = inner.output_events
        self.query_callbacks = inner.query_callbacks
        self.rate_limiter = inner.rate_limiter
        self.table_op = None
        self.tables = {}
        # absent deadlines: every partition's NFA shares the TIMER feed;
        # next_timer min-reduces across the [P] axis (_reduce_paux)
        self.needs_scheduler = inner.needs_scheduler
        self.timer_target = None
        self.inner_publish = None
        self.p = int(p_capacity)
        self.state = None
        self._receive_lock = inner._receive_lock
        for sid in self.prog.stream_ids:
            if sid not in key_fns:
                raise SiddhiAppCreationError(
                    f"partition has no key for pattern stream '{sid}'"
                )
        self.key_fns = key_fns
        self.schemas = schemas
        self._psteps = {
            sid: jax.jit(
                lambda pt, st, b, now, _sid=sid: self._pstep_impl(pt, st, b, now, _sid),
                donate_argnums=(1,),
            )
            for sid in self.prog.stream_ids
        }

    # routing shared with BaseQueryRuntime via delegation
    @property
    def publish_fn(self):
        return self._inner.publish_fn

    @publish_fn.setter
    def publish_fn(self, fn):
        self._inner.publish_fn = fn

    def route_output(self, out, now, decode):
        self._inner.route_output(out, now, decode)

    def _warn_aux(self, aux):
        self._inner._warn_aux(aux)

    def flush_aux_warnings(self):
        self._inner.flush_aux_warnings()

    def init_state(self, now: int = 0):
        one = self._inner.init_state(now)
        return jax.tree_util.tree_map(lambda x: _tile(x, self.p), one)

    def _pstep_impl(self, ptable, states, batch: EventBatch, now, stream_id: str):
        cols = {(stream_id, None, n): c for n, c in batch.cols.items()}
        cols[(stream_id, None, TS_ATTR)] = batch.ts
        keys, matched = self.key_fns[stream_id](Env(cols, now=now))
        active = batch.valid & (batch.kind == KIND_CURRENT) & matched
        pk, pu, pn, slot, _grp, povf = assign_slots(
            ptable["keys"], ptable["used"], ptable["n"], keys, active
        )
        # a lane allocated to a key seen for the FIRST time must start with
        # freshly-stamped token state: all lanes share the vmapped state and
        # have had their virgin tokens' absent deadlines advancing since app
        # start, so a late key would otherwise inherit an already-elapsed
        # absence window (reference: AbsentStreamPreStateProcessor is armed
        # at partition-INSTANCE creation, PartitionRuntime.java:256-315)
        fresh = pu & ~ptable["used"]
        init_lane = self._inner.init_state(now)

        def _do_refresh(st):
            def _refresh(cur, init):
                mask = fresh.reshape((self.p,) + (1,) * (cur.ndim - 1))
                return jnp.where(mask, jnp.broadcast_to(init, cur.shape), cur)

            return jax.tree_util.tree_map(_refresh, st, init_lane)

        # steady state allocates no lanes: skip the full-state rewrite
        states = jax.lax.cond(fresh.any(), _do_refresh, lambda st: st, states)
        is_timer = batch.valid & (batch.kind == KIND_TIMER)
        step = self._inner._make_step(stream_id)

        def one(state, p):
            sub_valid = (active & (slot == p)) | is_timer
            b2 = EventBatch(batch.ts, batch.kind, sub_valid, batch.cols)
            st, _ts, out, aux = step(state, {}, b2, now)
            return st, out, aux

        states2, outs, auxs = jax.vmap(one)(states, jnp.arange(self.p))
        # TIMER rows riding a stream batch reach every lane; outputs and
        # timer re-arms from lanes with no live key must be masked just like
        # the dedicated timer path does
        outs = EventBatch(
            outs.ts, outs.kind, outs.valid & pu[:, None], outs.cols
        )
        if "next_timer" in auxs:
            auxs = {
                **auxs,
                "next_timer": jnp.where(
                    pu, auxs["next_timer"], np.int64(NO_TIMER)
                ),
            }
        aux = _reduce_paux(auxs, povf)
        return {"keys": pk, "used": pu, "n": pn}, states2, outs, aux

    def _ptimer_impl(self, states, used, batch: EventBatch, now):
        def one(state):
            st, _ts, out, aux = self._inner._make_step(None)(state, {}, batch, now)
            return st, out, aux

        states2, outs, auxs = jax.vmap(one)(states)
        # only lanes holding a live key may emit/schedule — unused lanes
        # still carry armed virgin tokens (absent-at-start would fire on
        # every empty lane otherwise)
        outs = EventBatch(
            outs.ts, outs.kind, outs.valid & used[:, None], outs.cols
        )
        if "next_timer" in auxs:
            auxs = {
                **auxs,
                "next_timer": jnp.where(
                    used, auxs["next_timer"], np.int64(NO_TIMER)
                ),
            }
        return states2, outs, _reduce_paux(auxs)

    def prime(self, now: int) -> dict:
        """Arm absent-at-start deadlines across every partition lane."""
        from siddhi_tpu.core.query_runtime import BaseQueryRuntime

        with self._receive_lock:
            if self.state is None:
                self.state = BaseQueryRuntime._fresh(self.init_state(now))
            t = jax.vmap(self.prog.next_timer)(self.state["tok"]).min()
        return {"next_timer": t}

    def receive_timer_partitioned(self, ptable, batch: EventBatch, t_ms: int):
        with self._receive_lock:
            if self.state is None:
                from siddhi_tpu.core.query_runtime import BaseQueryRuntime

                self.state = BaseQueryRuntime._fresh(self.init_state(t_ms))
            if not hasattr(self, "_ptimer"):
                self._ptimer = jax.jit(self._ptimer_impl, donate_argnums=(0,))
            self.state, outs, aux = self._ptimer(
                self.state, ptable["used"], batch, jnp.asarray(t_ms, jnp.int64)
            )
        self._warn_aux(aux)
        return _flatten(outs), aux

    def receive_partitioned(self, ptable, batch: EventBatch, now: int, stream_id: str):
        with self._receive_lock:
            if self.state is None:
                from siddhi_tpu.core.query_runtime import BaseQueryRuntime

                self.state = BaseQueryRuntime._fresh(self.init_state())
            ptable, self.state, outs, aux = self._psteps[stream_id](
                ptable, self.state, batch, jnp.asarray(now, jnp.int64)
            )
        self._warn_aux(aux)
        return ptable, _flatten(outs), outs, aux


def _flatten(outs: EventBatch) -> EventBatch:
    """[P, K] partitioned output -> [K*P] flat batch ordered by output
    position first (temporal order), partition second."""
    def f(x):
        return jnp.swapaxes(x, 0, 1).reshape(-1)

    return EventBatch(
        ts=f(outs.ts),
        kind=f(outs.kind),
        valid=f(outs.valid),
        cols={n: f(c) for n, c in outs.cols.items()},
    )


class PartitionRuntime:
    """Host orchestration of one `partition with (...) begin ... end` block."""

    def __init__(
        self, partition: Partition, app_runtime, pid: str, query_ids=None
    ):
        self.partition = partition
        self.app = app_runtime
        self.pid = pid
        self.p = app_runtime._capacity_annotation(
            "app:partitionCapacity", DEFAULT_PARTITIONS
        )
        interner = app_runtime.interner

        # key executors per partitioned stream
        # (reference: Value/RangePartitionExecutor)
        self.key_fns: dict[str, Callable] = {}
        for pt in partition.partition_types:
            schema = app_runtime.stream_schemas.get(pt.stream_id)
            if schema is None:
                raise SiddhiAppCreationError(
                    f"partition: stream '{pt.stream_id}' is not defined"
                )
            scope = Scope(interner)
            scope.add_stream(pt.stream_id, schema.attr_types)
            if isinstance(pt, ValuePartitionType):
                from siddhi_tpu.core.groupby import _as_key_col

                ce = compile_expression(pt.expression, scope)
                if ce.type is AttrType.OBJECT:
                    raise SiddhiAppCreationError("cannot partition by OBJECT")

                def key_of(env, _ce=ce):
                    k = _as_key_col(_ce(env), _ce.type)
                    return k, jnp.ones_like(k, dtype=jnp.bool_)

            else:
                assert isinstance(pt, RangePartitionType)
                conds = []
                for rp in pt.ranges:
                    c = compile_expression(rp.condition, scope)
                    if c.type is not AttrType.BOOL:
                        raise SiddhiAppCreationError(
                            "range partition conditions must be boolean"
                        )
                    conds.append(c)

                def key_of(env, _conds=tuple(conds)):
                    key = None
                    matched = None
                    for i, c in enumerate(_conds):
                        m = c(env)
                        if key is None:
                            key = jnp.where(m, np.int64(i), np.int64(-1))
                            matched = m
                        else:
                            key = jnp.where(~matched & m, np.int64(i), key)
                            matched = matched | m
                    return key, matched  # unmatched rows are dropped

            self.key_fns[pt.stream_id] = key_of

        # shared partition key table (one key space per partition block,
        # reference: PartitionRuntime per-key instance map)
        self.ptable = {
            "keys": jnp.zeros((self.p,), jnp.int64),
            "used": jnp.zeros((self.p,), jnp.bool_),
            "n": jnp.zeros((), jnp.int32),
        }

        # inner (#stream) plumbing: [P]-shaped pub/sub
        self.inner_schemas: dict[str, StreamSchema] = {}
        self.inner_subscribers: dict[str, list] = {}

        self.queries: list[PartitionedQueryRuntime] = []
        if query_ids is None:
            # direct construction (app_runtime passes the shared
            # assignment): fall back to the same helper for this block
            from siddhi_tpu.query_api.annotation import find_annotation

            query_ids = []
            unnamed = 0
            for q in partition.queries:
                info = find_annotation(q.annotations, "info")
                qid = (
                    info.element("name") if info else None
                ) or f"{pid}_query{unnamed}"
                unnamed += 1
                query_ids.append((qid, q))
        for qid, q in query_ids:
            self._add_query(qid, q)

    def _add_query(self, qid: str, query: Query) -> None:
        app = self.app
        stream = query.input_stream
        from siddhi_tpu.query_api.execution import (
            JoinInputStream,
            StateInputStream,
        )

        if isinstance(stream, JoinInputStream):
            self._add_join_query(qid, query)
            return
        if isinstance(stream, StateInputStream):
            self._add_pattern_query(qid, query)
            return
        if not isinstance(stream, SingleInputStream):
            raise SiddhiAppCreationError(
                f"{type(stream).__name__} queries inside partitions are not "
                "supported yet"
            )
        is_inner = stream.is_inner
        if is_inner:
            in_schema = self.inner_schemas.get(stream.stream_id)
            if in_schema is None:
                raise SiddhiAppCreationError(
                    f"inner stream '#{stream.stream_id}' is not produced by an "
                    "earlier query in this partition"
                )
            key_of = None
        else:
            in_schema = app.stream_schemas.get(stream.stream_id)
            if in_schema is None:
                raise SiddhiAppCreationError(
                    f"stream '{stream.stream_id}' is not defined"
                )
            key_of = self.key_fns.get(stream.stream_id)
            if key_of is None:
                raise SiddhiAppCreationError(
                    f"partition has no key for stream '{stream.stream_id}'"
                )

        qr = PartitionedQueryRuntime(
            query, qid, in_schema, app.interner,
            p_capacity=self.p, key_of=key_of,
            group_capacity=app.group_capacity,
        )
        self.queries.append(qr)
        app.queries[qid] = qr

        out = query.output_stream
        inner_target = isinstance(out, InsertIntoStream) and out.is_inner
        if inner_target:
            self.inner_schemas[out.target] = StreamSchema(
                out.target, qr.out_schema.attrs
            )
            subs = self.inner_subscribers.setdefault(out.target, [])
            from siddhi_tpu.core.app_runtime import _make_insert_transform

            # honor `insert [current|expired|all] events into #T` and rewrite
            # inserted kinds to CURRENT, like the outer insert path
            transform = _make_insert_transform(out.output_events)

            def publish_inner(p_out, now, _subs=subs, _t=transform):
                p_out = _t(p_out)  # elementwise: works on the [P, K] lanes
                for fn in _subs:
                    fn(p_out, now)

            qr.inner_publish = publish_inner
        else:
            app._wire_insert(qr)

        decode = app._decode
        table_apply = self._attach_table_output(qr, query)

        if is_inner:
            def recv_inner(p_out, now, _qr=qr):
                flat, p_out2, aux = _qr.receive_inner(p_out, now)
                self._route(_qr, flat, p_out2, now, decode)
                if table_apply is not None:
                    table_apply(flat, now)
                app._maybe_schedule(_qr, aux)

            self.inner_subscribers[stream.stream_id].append(recv_inner)

            if qr.needs_scheduler:
                # TIMER batches for [P]-shaped inner inputs are tiled across
                # the partition axis (every partition's clock advances)
                def fire_inner(t_ms: int, _qr=qr, _schema=in_schema) -> None:
                    one = app._timer_batch(_schema, t_ms)
                    pbatch = jax.tree_util.tree_map(
                        lambda x: _tile(x, _qr.p), one
                    )
                    with app._process_lock:
                        flat, p_out2, aux = _qr.receive_inner(pbatch, t_ms)
                        self._route(_qr, flat, p_out2, t_ms, decode)
                    app._maybe_schedule(_qr, aux)

                qr.timer_target = fire_inner
        else:
            def receive(batch: EventBatch, now: int, _qr=qr) -> None:
                with app._process_lock:
                    self.ptable, flat, p_out, aux = _qr.receive_partitioned(
                        self.ptable, batch, now
                    )
                    self._route(_qr, flat, p_out, now, decode)
                    if table_apply is not None:
                        table_apply(flat, now)
                app._maybe_schedule(_qr, aux)

            app._junction(stream.stream_id).subscribe(
                receive, name=f"query.{qid}"
            )

            if qr.needs_scheduler:
                def fire(t_ms: int, _qr=qr, _schema=in_schema) -> None:
                    batch = app._timer_batch(_schema, t_ms)
                    with app._process_lock:
                        self.ptable, flat, p_out, aux = _qr.receive_partitioned(
                            self.ptable, batch, t_ms
                        )
                        self._route(_qr, flat, p_out, t_ms, decode)
                    app._maybe_schedule(_qr, aux)

                qr.timer_target = fire

    def _check_output_target(self, query: Query, allow_inner: bool = False) -> None:
        out = query.output_stream
        if not allow_inner and getattr(out, "is_inner", False):
            raise SiddhiAppCreationError(
                "#inner outputs from joins/patterns inside partitions are "
                "not supported yet"
            )

    def _attach_table_output(self, qr, query: Query):
        """Table writes from inside a partition apply OUTSIDE the vmapped
        step, on the flattened [P*K] output: every partition's rows merge
        into the ONE shared table in output order (reference: cloned inner
        runtimes all write the same shared table instance,
        PartitionRuntime.java:256-315 + TablePartitionTestCase).

        Returns an `apply(flat_batch, now)` host hook, or None."""
        from siddhi_tpu.core.table import compile_table_output

        app = self.app
        top = compile_table_output(
            query.output_stream, qr.out_schema, app.tables, app.interner
        )
        if top is None:
            return None
        target = query.output_stream.target
        tids = sorted(app.tables)

        @jax.jit
        def step(tstates, batch, now):
            aux = {}
            return top(tstates, batch, now, aux), aux

        def apply(flat: EventBatch, now: int) -> None:
            tstates = {tid: app.tables[tid].state for tid in tids}
            tstates, aux = step(tstates, flat, jnp.asarray(now, jnp.int64))
            for tid in tids:
                app.tables[tid].state = tstates[tid]
            app.tables[target].notify_change()
            qr._warn_aux(aux)

        return apply

    def _add_join_query(self, qid: str, query: Query) -> None:
        app = self.app
        self._check_output_target(query)
        join = query.input_stream
        schemas = []
        key_by_side = {}
        for side, s in (("l", join.left), ("r", join.right)):
            if s.is_inner:
                raise SiddhiAppCreationError(
                    "#inner streams on join sides inside partitions are not "
                    "supported yet"
                )
            sch = app.stream_schemas.get(s.stream_id)
            if sch is None:
                raise SiddhiAppCreationError(
                    "only plain streams can join inside partitions"
                )
            kf = self.key_fns.get(s.stream_id)
            if kf is None:
                raise SiddhiAppCreationError(
                    f"partition has no key for stream '{s.stream_id}'"
                )
            key_by_side[side] = kf
            schemas.append(sch)
        qr = PartitionedJoinQueryRuntime(
            query, qid, schemas[0], schemas[1], app.interner,
            p_capacity=self.p, key_of_by_side=key_by_side,
            group_capacity=app.group_capacity,
            join_capacity=app._capacity_annotation("app:joinCapacity", 512),
        )
        self.queries.append(qr)
        app.queries[qid] = qr
        app._wire_insert(qr)
        decode = app._decode
        table_apply = self._attach_table_output(qr, query)

        def receive_side(batch: EventBatch, now: int, side: str, _qr=qr) -> None:
            with app._process_lock:
                self.ptable, flat, _p_out, aux = _qr.receive_partitioned(
                    self.ptable, batch, now, side
                )
                _qr.route_output(flat, now, decode)
                if table_apply is not None:
                    table_apply(flat, now)

        if join.left.stream_id == join.right.stream_id:
            j = app._junction(join.left.stream_id)
            j.subscribe(
                lambda b, now: (receive_side(b, now, "l"), receive_side(b, now, "r")),
                name=f"query.{qid}",
            )
        else:
            app._junction(join.left.stream_id).subscribe(
                lambda b, now: receive_side(b, now, "l"),
                name=f"query.{qid}",
            )
            app._junction(join.right.stream_id).subscribe(
                lambda b, now: receive_side(b, now, "r"),
                name=f"query.{qid}",
            )

    def _add_pattern_query(self, qid: str, query: Query) -> None:
        app = self.app
        self._check_output_target(query)
        # guard the NFA builder's raw stream_schemas indexing with a named
        # error (fallback path when semantic analysis is disabled)
        from siddhi_tpu.query_api.execution import iter_state_streams

        for s in iter_state_streams(query.input_stream.state):
            if s.stream_id not in app.stream_schemas:
                raise SiddhiAppCreationError(
                    f"query '{qid}': pattern stream '{s.stream_id}' is not "
                    "defined (patterns consume streams, not tables or windows)"
                )
        qr = PartitionedPatternQueryRuntime(
            query, qid, app.stream_schemas, app.interner,
            p_capacity=self.p, key_fns=self.key_fns,
            group_capacity=app.group_capacity,
            token_capacity=app._capacity_annotation("app:patternCapacity", 128),
            count_capacity=app._capacity_annotation("app:countCapacity", 8),
            batch_size=app.batch_size,
        )
        self.queries.append(qr)
        app.queries[qid] = qr
        app._wire_insert(qr)
        decode = app._decode
        table_apply = self._attach_table_output(qr, query)

        def receive(batch: EventBatch, now: int, sid: str, _qr=qr) -> None:
            with app._process_lock:
                self.ptable, flat, _p_out, aux = _qr.receive_partitioned(
                    self.ptable, batch, now, sid
                )
                _qr.route_output(flat, now, decode)
                if table_apply is not None:
                    table_apply(flat, now)
                app._maybe_schedule(_qr, aux)

        for sid in qr.prog.stream_ids:
            app._junction(sid).subscribe(
                lambda b, now, _sid=sid: receive(b, now, _sid),
                name=f"query.{qid}",
            )

        if qr.needs_scheduler:
            from siddhi_tpu.core.app_runtime import _pattern_timer_batch

            def fire(t_ms: int, _qr=qr) -> None:
                batch = _pattern_timer_batch(t_ms)
                with app._process_lock:
                    flat, aux = _qr.receive_timer_partitioned(
                        self.ptable, batch, t_ms
                    )
                    _qr.route_output(flat, t_ms, decode)
                    if table_apply is not None:
                        table_apply(flat, t_ms)
                app._maybe_schedule(_qr, aux)

            qr.timer_target = fire

    def _route(self, qr, flat: EventBatch, p_out, now: int, decode) -> None:
        if qr.inner_publish is not None:
            qr.inner_publish(p_out, now)
            # callbacks on inner-targeted queries still see the flat view
            if qr.query_callbacks:
                qr.route_output(flat, now, decode)
        else:
            qr.route_output(flat, now, decode)
