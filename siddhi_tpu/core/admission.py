"""Per-app admission control: bounded ingress with overload policies.

One bursting tenant must degrade ITSELF, not the manager: without a bound,
a single app's ingest burst eats the host CPU (encode, dispatch) and the
device queue that every other app on the manager shares. The
`@app:admission(...)` annotation (validated as SA128, shared rule set with
the analyzer) puts a gate in front of every input handler of the app:

    @app:admission(rate.limit='50000', policy='shed_newest',
                   max.pending='8192', block.timeout='5 sec')

- `rate.limit` — events/second quota, enforced by a token bucket whose
  burst equals one second of quota (the same smoothing horizon as the
  EWMA rate trackers that report it).
- `max.pending` — bound on the app's buffered ingress (@async ring/queue
  depth); senders into an over-bound app hit the policy below.
- `policy` — what happens to events over quota/bound:
    block        back-pressure the sender until capacity frees (bounded by
                 `block.timeout`, default 5 sec; remainder sheds, counted)
    shed_newest  keep the head of the incoming call, drop the tail
    shed_oldest  keep the tail (freshest data), drop the head; on python-
                 queue @async junctions the oldest QUEUED events are
                 drained first
    error        raise AdmissionRejectedError to the sender

Shed/blocked counts are metered: `runtime.snapshot_status()['admission']`
(=> `/status.json`), Prometheus (`siddhi_admission_shed_total`,
`siddhi_admission_blocked_ms_total` via `manager.prometheus_text()`), and
the selfmon stream.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional

from siddhi_tpu.core.errors import SiddhiAppCreationError


class AdmissionRejectedError(RuntimeError):
    """Raised to the sender under `policy='error'` when the app is over its
    admission bound/quota."""


ADMISSION_POLICIES = ("block", "shed_oldest", "shed_newest", "error")
_DEFAULT_BLOCK_TIMEOUT_MS = 5_000


def _parse_time_ms(v) -> Optional[int]:
    from siddhi_tpu.core.supervision import _parse_time_ms as p

    return p(v)


@dataclass
class AdmissionConfig:
    policy: str = "shed_newest"
    rate_eps: Optional[float] = None  # events/second quota
    max_pending: Optional[int] = None  # bound on buffered ingress
    block_timeout_ms: int = _DEFAULT_BLOCK_TIMEOUT_MS


def iter_admission_annotation_problems(ann):
    """Yield one message per `@app:admission` problem — THE validation
    rules, shared by the runtime resolver and the analyzer's SA128."""
    keys = {k for k, _v in ann.elements}
    for k, v in ann.elements:
        if k == "policy":
            if str(v).strip().lower() not in ADMISSION_POLICIES:
                yield (
                    f"@app:admission policy '{v}' must be one of "
                    f"{ADMISSION_POLICIES}"
                )
        elif k == "rate.limit":
            try:
                ok = float(str(v).strip()) > 0
            except ValueError:
                ok = False
            if not ok:
                yield (
                    f"@app:admission rate.limit '{v}' must be a positive "
                    "events/second number"
                )
        elif k == "max.pending":
            try:
                ok = int(str(v).strip()) > 0
            except ValueError:
                ok = False
            if not ok:
                yield (
                    f"@app:admission max.pending '{v}' must be a positive "
                    "event count"
                )
        elif k == "block.timeout":
            if _parse_time_ms(v) is None:
                yield (
                    f"@app:admission block.timeout '{v}' must be a time "
                    "constant (e.g. '5 sec')"
                )
        else:
            yield (
                f"unknown @app:admission option "
                f"'{k if k is not None else v}' (expected policy, "
                "rate.limit, max.pending, block.timeout)"
            )
    if "rate.limit" not in keys and "max.pending" not in keys:
        yield (
            "@app:admission needs at least one bound: rate.limit (events/s) "
            "or max.pending (buffered events)"
        )


def resolve_admission_annotation(ann) -> AdmissionConfig:
    """AdmissionConfig from `@app:admission(...)`. Raises
    SiddhiAppCreationError on malformed options — the runtime analog of
    SA128."""
    for problem in iter_admission_annotation_problems(ann):
        raise SiddhiAppCreationError(problem)
    cfg = AdmissionConfig()
    v = ann.element("policy")
    if v is not None:
        cfg.policy = str(v).strip().lower()
    v = ann.element("rate.limit")
    if v is not None:
        cfg.rate_eps = float(v)
    v = ann.element("max.pending")
    if v is not None:
        cfg.max_pending = int(v)
    v = ann.element("block.timeout")
    if v is not None:
        cfg.block_timeout_ms = _parse_time_ms(v)
    return cfg


class AdmissionController:
    """One per app (owned by SiddhiAppRuntime). Thread-safe: concurrent
    senders contend on one lock around the token-bucket arithmetic only —
    blocking sleeps happen outside it."""

    def __init__(self, app_name: str, config: AdmissionConfig) -> None:
        self.app_name = app_name
        self.config = config
        self._lock = threading.Lock()
        # token bucket: burst = one second of quota (>= 1 so a quota under
        # 1 ev/s still admits single events)
        self._burst = max(config.rate_eps or 0.0, 1.0)
        self._tokens = self._burst
        self._t_last = time.monotonic()
        self.admitted = 0
        self.shed = 0
        self.blocked_ms = 0.0
        self.rejected = 0
        # black-box trigger hook (observability/blackbox.py): called with
        # ('admission', detail) when events are shed; None = one attribute
        # check (the recorder's debounce absorbs shed bursts)
        self.on_incident = None

    # ---- token bucket ----------------------------------------------------

    def _refill(self, now: float) -> None:
        rate = self.config.rate_eps
        if rate is None:
            return
        self._tokens = min(
            self._burst, self._tokens + (now - self._t_last) * rate
        )
        self._t_last = now

    def _take(self, n: int) -> int:
        """Take up to n tokens; returns how many were granted."""
        if self.config.rate_eps is None:
            return n
        with self._lock:
            now = time.monotonic()
            self._refill(now)
            k = int(min(n, self._tokens))
            self._tokens -= k
            return k

    def _refund(self, k: int) -> None:
        """Return unused tokens to the bucket (events that were quota-
        granted but not admitted — pending-bound overflow, clean reject)."""
        if self.config.rate_eps is None or k <= 0:
            return
        with self._lock:
            self._tokens = min(self._burst, self._tokens + k)

    def _pending_room(self, junction, n: int) -> int:
        """How many of n rows fit under max.pending right now."""
        mp = self.config.max_pending
        if mp is None:
            return n
        room = mp - junction.queued()
        return max(0, min(n, room))

    # ---- admission -------------------------------------------------------

    def admit(self, n: int, junction) -> tuple[int, int]:
        """Admit up to `n` incoming rows against the quota and the pending
        bound. Returns (start, end): the slice of the incoming rows that
        was admitted (shed_oldest drops the head, every other policy drops
        the tail). Raises AdmissionRejectedError under policy='error'."""
        if n <= 0:
            return 0, 0
        policy = self.config.policy
        taken = self._take(n)
        granted = min(taken, self._pending_room(junction, n))
        if granted >= n:
            self.admitted += n
            return 0, n
        queued_shed = 0
        if policy == "block":
            # tokens drained for room-refused events go back before the
            # wait — _block_for re-takes them as capacity frees
            self._refund(taken - granted)
            granted += self._block_for(n - granted, junction)
        elif policy == "error":
            # put the WHOLE take back: the sender gets a clean reject, not
            # a partially-drained bucket
            self._refund(taken)
            self.rejected += n
            raise AdmissionRejectedError(
                f"app '{self.app_name}': over admission "
                f"{'quota' if self.config.rate_eps else 'bound'} "
                f"({n} events, {granted} admissible)"
            )
        elif policy == "shed_oldest":
            # only ROOM-blocked events (already token-granted) may displace
            # older queued events: freeing queue slots mints no quota, so
            # token-refused events stay refused and the rate limit holds
            want = taken - granted
            if want > 0:
                queued_shed = self._shed_queued(junction, want)
                granted += min(queued_shed, want)
            self._refund(taken - granted)
        else:  # shed_newest
            # quota tokens drained for events the pending bound then
            # refused must go back: otherwise a full queue starves the
            # sender of quota it never used once the queue frees
            self._refund(taken - granted)
        dropped = n - granted
        self.admitted += granted
        # queued events destroyed to make room were admitted once — they
        # count as shed too, or the meter under-reports the loss
        self.shed += dropped + queued_shed
        if dropped + queued_shed:
            oi = self.on_incident
            if oi is not None:
                oi(
                    "admission",
                    f"shed {dropped + queued_shed} events "
                    f"(policy={policy}, total_shed={self.shed})",
                )
        if policy == "shed_oldest":
            # keep the TAIL: the freshest events survive
            return dropped, n
        return 0, granted

    def _block_for(self, need: int, junction) -> int:
        """Back-pressure: wait (in small sleeps) until `need` more rows are
        admissible or block.timeout elapses; returns how many more were
        granted."""
        deadline = time.monotonic() + self.config.block_timeout_ms / 1000.0
        got = 0
        t0 = time.monotonic()
        while got < need:
            now = time.monotonic()
            if now >= deadline:
                break
            time.sleep(0.001)
            taken = self._take(need - got)
            k = min(taken, self._pending_room(junction, need - got))
            self._refund(taken - k)
            got += k
        self.blocked_ms += (time.monotonic() - t0) * 1000.0
        return got

    @staticmethod
    def _shed_queued(junction, n: int) -> int:
        """Drop up to n of the OLDEST queued events from a python-queue
        @async junction (freshest-data-wins). Native MPSC rings are single-
        consumer — popping from the admission thread would race the drain
        worker — and synchronous junctions hold no queue; both shed from
        the incoming call instead."""
        q = getattr(junction, "_queue", None)
        if q is None or not getattr(junction, "is_async", False):
            return 0
        import queue as _q

        shed = 0
        for _ in range(n):
            try:
                q.get_nowait()
                shed += 1
            except _q.Empty:
                break
        return shed

    # ---- surfacing -------------------------------------------------------

    def describe_state(self) -> dict:
        d: dict = {
            "policy": self.config.policy,
            "admitted": self.admitted,
            "shed": self.shed,
            "blocked_ms": round(self.blocked_ms, 3),
            "rejected": self.rejected,
        }
        if self.config.rate_eps is not None:
            d["rate_limit_eps"] = self.config.rate_eps
        if self.config.max_pending is not None:
            d["max_pending"] = self.config.max_pending
        return d


class AdmittedInputHandler:
    """InputHandler facade applying the app's AdmissionController before
    delegating (wraps the playback handler too — admission is outermost)."""

    def __init__(self, inner, controller: AdmissionController, junction):
        self._inner = inner
        self._ctl = controller
        self._junction = junction

    def send(self, data, timestamp=None):
        lo, hi = self._ctl.admit(1, self._junction)
        if hi > lo:
            self._inner.send(data, timestamp)

    def send_many(self, rows, timestamps=None):
        lo, hi = self._ctl.admit(len(rows), self._junction)
        if hi <= lo:
            return
        self._inner.send_many(
            rows[lo:hi],
            timestamps[lo:hi] if timestamps is not None else None,
        )

    def send_columns(self, timestamps, cols, now=None):
        n = len(timestamps)
        lo, hi = self._ctl.admit(n, self._junction)
        if hi <= lo:
            return
        if lo == 0 and hi == n:
            self._inner.send_columns(timestamps, cols, now)
            return
        self._inner.send_columns(
            timestamps[lo:hi], {k: v[lo:hi] for k, v in cols.items()}, now
        )
