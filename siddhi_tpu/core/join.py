"""Join runtime: two windowed sides probing each other on device.

Reference: query/input/stream/join/JoinProcessor.java:34-200 — each arriving
event locks, probes the *other* side's window via FindableProcessor.find,
builds joined StateEvents; JoinInputStreamParser.java wires
filter -> preJoinProcessor -> window -> postJoinProcessor per side, with
left/right/full outer null-filling and unidirectional trigger control.

Here each side's probe is one masked [B, W] condition evaluation on device:
arriving rows broadcast against the other window's stored contents, matched
pairs compacted to a fixed-capacity joined output batch, outer-join misses
ride an extra "null partner" column of the mask.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.event import (
    EventBatch,
    KIND_CURRENT,
    KIND_EXPIRED,
    KIND_TIMER,
    StreamSchema,
)
from siddhi_tpu.core.executor import Env, Scope, TS_ATTR, compile_expression
from siddhi_tpu.core.flow import Flow
from siddhi_tpu.core.selector import CompiledSelector
from siddhi_tpu.core.types import AttrType, null_value
from siddhi_tpu.core.windows import WindowStage, make_window
from siddhi_tpu.query_api.execution import (
    Filter,
    JoinEventTrigger,
    JoinInputStream,
    JoinType,
    OutputEventsFor,
    Query,
    SingleInputStream,
    StreamFunctionHandler,
    WindowHandler,
)

DEFAULT_JOIN_CAPACITY = 512


class NoWindow(WindowStage):
    """A join side with no #window: arrivals probe but are never retained
    (reference: JoinInputStreamParser wraps windowless sides in a zero-length
    LengthWindowProcessor, JoinInputStreamParser.java:128-146)."""

    def __init__(self, schema: StreamSchema, ref: str):
        self.schema = schema
        self.ref = ref

    def init_state(self):
        return {}

    def apply(self, state, flow: Flow):
        b = flow.batch
        empty = EventBatch(b.ts, b.kind, jnp.zeros_like(b.valid), b.cols)
        return state, dataclasses.replace(flow, batch=empty)

    def view(self, state):
        cols = {
            n: jnp.zeros((1,), a.dtype)
            for n, a in self.schema.empty_batch(1).cols.items()
        }
        return cols, jnp.zeros((1,), jnp.int64), jnp.zeros((1,), jnp.bool_)

    def view_seq(self, state):
        return jnp.full((1,), -1, jnp.int64)


class TableSide:
    """A join side backed by a shared findable: a table (reference:
    TableWindowProcessor — probe-only, never triggers) or a named window
    (reference: WindowWindowProcessor — its emission stream actively drives
    the join while probes read the shared buffer)."""

    is_table = True

    def __init__(self, stream: SingleInputStream, table):
        if stream.handlers:
            raise SiddhiAppCreationError(
                f"'{stream.stream_id}' cannot carry filters/windows "
                "on a join side"
            )
        self.stream_id = stream.stream_id
        self.ref = stream.ref
        self.schema = table.schema
        self.table = table
        self.window = None
        # tables are passive probe targets; named windows also trigger
        self.passive = not getattr(table, "is_named_window", False)

    def init_state(self):
        return {}

    def filter_batch(self, batch: EventBatch, now) -> EventBatch:
        return batch

    def probe_view(self, state_slice, tstates):
        return self.table.view(tstates[self.table.table_id])

    def probe_seq(self, state_slice):
        return None  # findables carry no admission order


class JoinSide:
    """One side of the join: pre-window filters + window stage."""

    is_table = False
    passive = False

    def __init__(
        self,
        stream: SingleInputStream,
        schema: StreamSchema,
        scope: Scope,
    ):
        self.stream_id = stream.stream_id
        self.ref = stream.ref
        self.schema = schema
        side_scope = scope.child()
        side_scope.default_ref = self.ref
        self.pre_filters = []
        self.window: WindowStage | None = None
        for h in stream.handlers:
            if isinstance(h, Filter):
                if self.window is not None:
                    raise SiddhiAppCreationError(
                        "filters after the window are not supported on join sides"
                    )
                cond = compile_expression(h.expression, side_scope)
                if cond.type is not AttrType.BOOL:
                    raise SiddhiAppCreationError("filter must be a boolean expression")
                self.pre_filters.append(cond)
            elif isinstance(h, WindowHandler):
                if self.window is not None:
                    raise SiddhiAppCreationError("only one window per join side")
                self.window = make_window(h.window, schema, self.ref, side_scope)
            elif isinstance(h, StreamFunctionHandler):
                raise SiddhiAppCreationError(
                    f"stream function '{h.name}' not supported on join sides yet"
                )
        if self.window is None:
            self.window = NoWindow(schema, self.ref)

    def init_state(self):
        return self.window.init_state()

    def probe_view(self, state_slice, tstates):
        return self.window.view(state_slice)

    def probe_seq(self, state_slice):
        """Window admission seq per view slot (lineage), or None."""
        return self.window.view_seq(state_slice)

    def filter_batch(self, batch: EventBatch, now) -> EventBatch:
        if not self.pre_filters:
            return batch
        cols = {(self.ref, None, n): c for n, c in batch.cols.items()}
        cols[(self.ref, None, TS_ATTR)] = batch.ts
        env = Env(cols, now=now)
        mask = None
        for c in self.pre_filters:
            m = c(env)
            mask = m if mask is None else (mask & m)
        is_timer = batch.kind == KIND_TIMER  # timers bypass filters
        return EventBatch(
            batch.ts, batch.kind, batch.valid & (is_timer | mask), batch.cols
        )


class CompiledJoin:
    """Device-side join core: per-arrival-side step producing a joined batch
    whose columns carry both refs (left primary, right in extra cols)."""

    def __init__(
        self,
        join: JoinInputStream,
        left_schema: StreamSchema,
        right_schema: StreamSchema,
        scope: Scope,
        out_capacity: int = DEFAULT_JOIN_CAPACITY,
        output_expired: bool = False,
        tables: Optional[dict] = None,
    ):
        tables = tables or {}

        def make_side(stream, schema):
            t = tables.get(stream.stream_id)
            if t is not None:
                return TableSide(stream, t)
            return JoinSide(stream, schema, scope)

        self.left = make_side(join.left, left_schema)
        self.right = make_side(join.right, right_schema)
        if self.left.passive and self.right.passive:
            raise SiddhiAppCreationError("cannot join two tables; use a store query")
        if self.left.ref == self.right.ref:
            raise SiddhiAppCreationError(
                f"join sides must have distinct references; alias one: "
                f"'from {self.left.stream_id} as a join ...'"
            )
        self.join_type = join.join_type
        self.out_capacity = int(out_capacity)
        self.output_expired = output_expired
        # unidirectional narrows the trigger side
        # (reference: JoinInputStreamParser.java:214-231)
        trigger = join.trigger
        if join.unidirectional == "left":
            if self.left.passive:
                raise SiddhiAppCreationError(
                    "unidirectional cannot be set on the table side of a join"
                )
            trigger = JoinEventTrigger.LEFT
        elif join.unidirectional == "right":
            if self.right.passive:
                raise SiddhiAppCreationError(
                    "unidirectional cannot be set on the table side of a join"
                )
            trigger = JoinEventTrigger.RIGHT
        self.emit_left = (
            trigger in (JoinEventTrigger.ALL, JoinEventTrigger.LEFT)
            and not self.left.passive
        )
        self.emit_right = (
            trigger in (JoinEventTrigger.ALL, JoinEventTrigger.RIGHT)
            and not self.right.passive
        )
        self.on = None
        if join.on is not None:
            cond = compile_expression(join.on, scope)
            if cond.type is not AttrType.BOOL:
                raise SiddhiAppCreationError("join 'on' must be a boolean expression")
            self.on = cond
        # lineage (observability/lineage.py): when True the step emits
        # `__lin.*` aux lanes — per matched output row the probe-row index
        # and the partner ring's admission seq. Set by
        # JoinQueryRuntime.arm_lineage before the first trace.
        self.lineage = False

    def init_state(self):
        return {"l": self.left.init_state(), "r": self.right.init_state()}

    # ---- device step for one arriving side -------------------------------

    def step(self, state, batch: EventBatch, now, side: str, tstates=None):
        """side: 'l' | 'r'. Returns (state', joined Flow, aux)."""
        arr = self.left if side == "l" else self.right
        other = self.right if side == "l" else self.left
        other_key = "r" if side == "l" else "l"
        emits = self.emit_left if side == "l" else self.emit_right
        batch = arr.filter_batch(batch, now)
        aux: dict = {}
        if self.lineage:
            from siddhi_tpu.observability.lineage import LIN

            # the arriving side's window admissions: its filter-passing
            # CURRENT rows (table/named-window arrivals never re-buffer)
            aux[LIN + "admit"] = (
                batch.valid & (batch.kind == KIND_CURRENT)
                if not arr.is_table
                else jnp.zeros_like(batch.valid)
            )

        vcols, vts, vmask = other.probe_view(state[other_key], tstates or {})
        vseq = other.probe_seq(state[other_key]) if self.lineage else None

        # probe 1: arriving CURRENT rows against the other window
        # (reference: preJoinProcessor — probe happens BEFORE own-window insert)
        cur_rows = batch.valid & (batch.kind == KIND_CURRENT)

        if arr.is_table:
            # named-window side: arrivals are the window's emission stream —
            # they probe the other side but never re-buffer (the shared window
            # state already holds them); its EXPIRED emissions feed probe 2
            wstate = state[side]
            exp_src = batch
        else:
            # own-window insert; its EXPIRED output feeds probe 2
            flow_in = Flow(batch=batch, ref=arr.ref, now=now)
            wstate, wflow = arr.window.apply(state[side], flow_in)
            if "next_timer" in wflow.aux:
                aux["next_timer"] = wflow.aux["next_timer"]
            exp_src = wflow.batch

        probes = [(batch, cur_rows, np.int8(KIND_CURRENT))]
        if self.output_expired and emits:
            exp_rows = exp_src.valid & (exp_src.kind == KIND_EXPIRED)
            probes.append((exp_src, exp_rows, np.int8(KIND_EXPIRED)))
        if not emits:
            probes = []

        joined = self._assemble(
            probes, arr, other, vcols, vts, vmask, now, side, aux, tstates,
            vseq=vseq,
        )

        new_state = dict(state)
        new_state[side] = wstate
        return new_state, joined, aux

    def _assemble(
        self, probes, arr, other, vcols, vts, vmask, now, side, aux,
        tstates=None, vseq=None,
    ):
        """Evaluate the on-condition for each probe set, compact matched pairs
        (plus outer misses) into one fixed-capacity joined Flow."""
        cap = self.out_capacity
        w = vmask.shape[0]
        outer = (
            self.join_type is JoinType.FULL_OUTER
            or (side == "l" and self.join_type is JoinType.LEFT_OUTER)
            or (side == "r" and self.join_type is JoinType.RIGHT_OUTER)
        )

        if probes:
            row_ts = jnp.concatenate([b.ts for b, _, _ in probes])
            row_mask = jnp.concatenate([m for _, m, _ in probes])
            row_kind = jnp.concatenate(
                [jnp.full(m.shape, k, jnp.int8) for _, m, k in probes]
            )
            row_cols = {
                n: jnp.concatenate([b.cols[n] for b, _, _ in probes])
                for n in probes[0][0].cols
            }
        else:  # non-triggering side: empty probe set
            row_ts = jnp.zeros((1,), jnp.int64)
            row_mask = jnp.zeros((1,), jnp.bool_)
            row_kind = jnp.zeros((1,), jnp.int8)
            row_cols = {
                n: jnp.zeros((1,), a.dtype)
                for n, a in arr.schema.empty_batch(1).cols.items()
            }

        env_cols = {(arr.ref, None, n): c[:, None] for n, c in row_cols.items()}
        env_cols[(arr.ref, None, TS_ATTR)] = row_ts[:, None]
        env_cols.update({(other.ref, None, n): c[None, :] for n, c in vcols.items()})
        env_cols[(other.ref, None, TS_ATTR)] = vts[None, :]
        env = Env(env_cols, now=now)

        pair = row_mask[:, None] & vmask[None, :]
        if self.on is not None:
            pair = pair & self.on(env)

        if outer:
            missed = row_mask & ~pair.any(axis=1)
            pair = jnp.concatenate([pair, missed[:, None]], axis=1)  # col w = nulls
        wj = pair.shape[1]

        n_matches = pair.sum()
        aux["join_overflow"] = n_matches > cap

        flat = pair.reshape(-1)
        # compact match indices WITHOUT a device sort (nonzero lowers to one):
        # rank matched cells by prefix count and scatter their indices
        rank = jnp.cumsum(flat.astype(jnp.int32)) - flat
        pos = jnp.where(flat & (rank < cap), rank, cap)
        idx = (
            jnp.full((cap,), -1, jnp.int32)
            .at[pos]
            .set(jnp.arange(flat.shape[0], dtype=jnp.int32), mode="drop")
        )
        valid_out = idx >= 0
        pi = jnp.clip(idx // wj, 0, row_mask.shape[0] - 1)
        pj_raw = jnp.where(idx >= 0, idx % wj, w)
        is_null_partner = pj_raw >= w
        pj = jnp.clip(pj_raw, 0, w - 1)

        def partner_col(name, t):
            base = vcols[name][pj]
            return jnp.where(is_null_partner, np.asarray(null_value(t), base.dtype), base)

        if self.lineage:
            from siddhi_tpu.observability.lineage import LIN

            # per matched output row: the triggering probe-row index and
            # the partner window's admission seq (-1 = null/unknown) —
            # the host recorder turns these into the (left seq, right seq)
            # provenance pair (observability/lineage.py JoinQueryLineage)
            aux[LIN + "j_pi"] = jnp.where(valid_out, pi, np.int32(-1))
            if vseq is not None:
                aux[LIN + "j_pseq"] = jnp.where(
                    valid_out & ~is_null_partner, vseq[pj], np.int64(-1)
                )
            else:
                # no admission order on this partner (batch window, table,
                # named window): -2 = "partner unknown" — the recorder
                # flags the record approximate, distinct from -1 = "outer
                # join, legitimately no partner"
                aux[LIN + "j_pseq"] = jnp.where(
                    valid_out & ~is_null_partner,
                    np.int64(-2), np.int64(-1),
                )

        arr_out = {n: c[pi] for n, c in row_cols.items()}
        other_out = {
            n: partner_col(n, t) for n, t in other.schema.attr_types.items()
        }
        other_ts = jnp.where(is_null_partner, np.int64(0), vts[pj])

        out_ts = row_ts[pi]
        # primary batch always carries LEFT-side cols for a stable selector
        # layout; only the per-ref timestamps depend on the arrival side
        if side == "l":
            left_cols, right_cols = arr_out, other_out
            left_ts, right_ts = out_ts, other_ts
        else:
            left_cols, right_cols = other_out, arr_out
            left_ts, right_ts = other_ts, out_ts

        batch = EventBatch(out_ts, row_kind[pi], valid_out, left_cols)
        extra = {(self.right.ref, None, n): c for n, c in right_cols.items()}
        extra[(self.right.ref, None, TS_ATTR)] = right_ts
        extra[(self.left.ref, None, TS_ATTR)] = left_ts
        return Flow(
            batch=batch, ref=self.left.ref, now=now, extra_cols=extra, aux=aux,
            tables=tstates or {},
        )


from siddhi_tpu.core.query_runtime import BaseQueryRuntime


class JoinQueryRuntime(BaseQueryRuntime):
    """Compiled join query + device state + host routing
    (reference: JoinStreamRuntime + QueryRuntime)."""

    def __init__(
        self,
        query: Query,
        query_id: str,
        left_schema: StreamSchema,
        right_schema: StreamSchema,
        interner,
        group_capacity: Optional[int] = None,
        join_capacity: int = DEFAULT_JOIN_CAPACITY,
        tables: Optional[dict] = None,
        findables: Optional[dict] = None,
    ):
        join = query.input_stream
        assert isinstance(join, JoinInputStream)
        self.query = query
        self.query_id = query_id

        scope = Scope(interner)
        self._scope = scope
        lref, rref = join.left.ref, join.right.ref
        scope.add_stream(lref, left_schema.attr_types)
        scope.add_stream(rref, right_schema.attr_types)
        scope.default_ref = lref
        for t in (tables or {}).values():
            scope.add_table(t)

        output_expired = query.output_stream.output_events is not OutputEventsFor.CURRENT
        self.join = CompiledJoin(
            join,
            left_schema,
            right_schema,
            scope,
            out_capacity=join_capacity,
            output_expired=output_expired,
            tables=findables if findables is not None else tables,
        )
        # findable join sides that are NOT app tables (named windows): their
        # live state is read-only threaded into the step
        self.join_findables = {}
        for side_obj in (self.join.left, self.join.right):
            if side_obj.is_table and side_obj.table.table_id not in (tables or {}):
                self.join_findables[side_obj.table.table_id] = side_obj.table
        combined_attrs = [
            (n, t) for n, t in left_schema.attrs
        ] + [(n, t) for n, t in right_schema.attrs]
        self.selector = CompiledSelector(
            query.selector,
            scope,
            input_attrs=combined_attrs,
            batch_mode=False,
            group_capacity=group_capacity,
        )
        self._setup_output(query, query_id)
        self._attach_tables(tables, interner)

        self.needs_scheduler = {
            "l": not self.join.left.is_table and self.join.left.window.needs_scheduler,
            "r": not self.join.right.is_table and self.join.right.window.needs_scheduler,
        }
        # findable sides have no junction of their own; active (named-window)
        # sides are instead driven by the window's emission junction
        self.table_sides = {
            "l": self.join.left.is_table,
            "r": self.join.right.is_table,
        }
        self.window_sides = {
            "l": self.join.left.table
            if self.join.left.is_table and not self.join.left.passive
            else None,
            "r": self.join.right.table
            if self.join.right.is_table and not self.join.right.passive
            else None,
        }
        self.side_schemas = {"l": left_schema, "r": right_schema}
        self.timer_targets: dict[str, object] = {}
        self._steps = {
            "l": jax.jit(
                lambda st, ts, b, now: self._step_impl(st, ts, b, now, "l"),
                donate_argnums=(0,),
            ),
            "r": jax.jit(
                lambda st, ts, b, now: self._step_impl(st, ts, b, now, "r"),
                donate_argnums=(0,),
            ),
        }

    def init_state(self):
        return {"join": self.join.init_state(), "sel": self.selector.init_state()}

    def describe_state(self) -> dict:
        """Introspection: per-side window buffers (table/named-window sides
        are shared findables reported under their own component)."""
        d = super().describe_state()
        for key, side in (("left", self.join.left), ("right", self.join.right)):
            w = getattr(side, "window", None)
            if w is None:
                d[key] = {"type": "findable", "ref": side.stream_id}
                continue
            sk = "l" if key == "left" else "r"
            # under the receive lock: the step donates old state buffers, so
            # an unlocked read could touch already-deleted device arrays
            with self._receive_lock:
                d[key] = (
                    w.describe_state(self.state["join"][sk])
                    if self.state is not None
                    else {"type": type(w).__name__, "fill": 0}
                )
        return d

    def arm_lineage(self, cfg) -> None:
        """Enable provenance recording (@app:lineage): the join step emits
        `__lin.*` lanes — (probe row, partner ring seq) per matched output
        row — feeding a JoinQueryLineage. Must run before the first trace;
        emissions are untouched."""
        from siddhi_tpu.observability.lineage import JoinQueryLineage

        self.join.lineage = True
        self.lineage = JoinQueryLineage(
            cfg, self.query_id, self._published_kinds(),
            left_stream=self.join.left.stream_id,
            right_stream=self.join.right.stream_id,
            batch_capacity=0,  # recorder sizes probes off the in-lane
        )

    def _step_impl(self, state, tstates, batch: EventBatch, now, side: str):
        jstate, flow, aux = self.join.step(state["join"], batch, now, side, tstates)
        sel_state, out = self.selector.apply(state["sel"], flow)
        if self.table_op is not None:
            tstates = self.table_op(tstates, out, now, flow.aux)
        aux.update(flow.aux)
        if self.lineage is not None:
            from siddhi_tpu.core.event import KIND_CURRENT
            from siddhi_tpu.observability.lineage import LIN

            aux[LIN + "in"] = batch.valid & (batch.kind == KIND_CURRENT)
            aux[LIN + "in_ts"] = batch.ts
            aux[LIN + "out_valid"] = out.valid
            aux[LIN + "out_kind"] = out.kind
            aux[LIN + "out_ts"] = out.ts
        return {"join": jstate, "sel": sel_state}, tstates, out, aux

    def receive(self, batch: EventBatch, now: int, side: str):
        with self._receive_lock:
            if self.state is None:
                self.state = self._fresh(self.init_state())
            tstates = self._collect_table_states()
            timed = self._need_step_clock()
            if timed:
                import time as _time

                t0 = _time.perf_counter_ns()
            self.state, tstates, out, aux = self._steps[side](
                self.state, tstates, batch, jnp.asarray(now, dtype=jnp.int64)
            )
            if timed:
                # one jitted program per join side: the telemetry component
                # embeds the side (see BaseQueryRuntime._observe_step)
                self._observe_step(
                    self._steps[side], (side, int(batch.ts.shape[0])),
                    _time.perf_counter_ns() - t0,
                )
            self._writeback_table_states(tstates)
            lin = self.lineage
            if lin is not None:
                # under the receive lock: recorder order == dispatch order
                aux = self._lin_observe(lin, aux, now, tag=side)
        self._warn_aux(aux)
        return out, aux
