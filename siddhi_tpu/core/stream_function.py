"""Stream functions / stream processors: multi-attribute-emitting chain stages.

Reference: query/processor/stream/function/StreamFunctionProcessor.java +
Pol2CartStreamFunctionProcessor.java (appends cartesian x/y), and
query/processor/stream/LogStreamProcessor.java (event tracing pass-through).
Custom ones register via @extension("stream_function", name): factory
`(params: list[CompiledExpr], schema_attrs, ref, scope) -> StreamFunctionStage`.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.executor import CompiledExpr, Env, Scope
from siddhi_tpu.core.flow import Flow
from siddhi_tpu.core.types import AttrType, PHYSICAL_DTYPE


class StreamFunctionStage:
    """Appends computed attribute columns to the flowing batch
    (reference: StreamFunctionProcessor.process attaching outputData)."""

    def __init__(
        self,
        ref: str,
        new_attrs: list[tuple[str, AttrType]],
        fn: Callable[[Env], dict[str, jnp.ndarray]],
    ):
        self.ref = ref
        self.new_attrs = new_attrs
        self.fn = fn

    def apply(self, flow: Flow) -> Flow:
        import dataclasses

        env = flow.env()
        new_cols = self.fn(env)
        cols = dict(flow.batch.cols)
        for name, t in self.new_attrs:
            col = jnp.broadcast_to(
                new_cols[name].astype(PHYSICAL_DTYPE[t]), flow.batch.valid.shape
            )
            cols[name] = col
        batch = EventBatch(flow.batch.ts, flow.batch.kind, flow.batch.valid, cols)
        return dataclasses.replace(flow, batch=batch)


class LogStage:
    """#log([priority,] message) — host-side event tracing via debug callback
    (reference: LogStreamProcessor)."""

    new_attrs: list = []

    def __init__(self, ref: str, message: str, stream_id: str):
        self.ref = ref
        self.message = message
        self.stream_id = stream_id

    def apply(self, flow: Flow) -> Flow:
        import logging

        from siddhi_tpu.utils.backend import host_callbacks_supported

        if not host_callbacks_supported():
            # backends without host callbacks (e.g. tunneled chips): #log
            # degrades to a pass-through with a one-time notice
            if not getattr(self, "_warned", False):
                self._warned = True
                logging.getLogger(f"siddhi_tpu.log.{self.stream_id}").warning(
                    "#log disabled: this backend has no host callbacks"
                )
            return flow

        msg = self.message
        sid = self.stream_id

        def log_rows(valid, ts, kinds):
            import numpy as np

            n = int(np.asarray(valid).sum())
            if n:
                logging.getLogger(f"siddhi_tpu.log.{sid}").info(
                    "%s : %d event(s), ts=%s",
                    msg, n, np.asarray(ts)[np.asarray(valid)].tolist(),
                )

        jax.debug.callback(log_rows, flow.batch.valid, flow.batch.ts, flow.batch.kind)
        return flow


def make_stream_function(
    handler, schema_attrs: dict[str, AttrType], ref: str, scope: Scope, stream_id: str
):
    """Dispatch a #ns:name(params) handler to a built-in or extension stage."""
    from siddhi_tpu.core.executor import compile_expression
    from siddhi_tpu.core.extension import lookup
    from siddhi_tpu.query_api.expression import Constant

    name = (
        f"{handler.namespace}:{handler.name}" if handler.namespace else handler.name
    ).lower()

    if name == "log":
        msg = "LOG"
        for p in handler.parameters:
            if isinstance(p, Constant) and isinstance(p.value, str):
                msg = p.value
        return LogStage(ref, msg, stream_id)

    if name == "pol2cart":
        params = [compile_expression(p, scope) for p in handler.parameters]
        if len(params) not in (2, 3):
            raise SiddhiAppCreationError("pol2Cart(theta, rho[, z]) needs 2-3 args")

        def fn(env: Env, _p=params):
            theta = _p[0](env).astype(jnp.float32)
            rho = _p[1](env).astype(jnp.float32)
            out = {
                "x": rho * jnp.cos(jnp.deg2rad(theta)),
                "y": rho * jnp.sin(jnp.deg2rad(theta)),
            }
            if len(_p) > 2:
                out["z"] = _p[2](env).astype(jnp.float32)
            return out

        attrs = [("x", AttrType.DOUBLE), ("y", AttrType.DOUBLE)]
        if len(params) > 2:
            attrs.append(("z", AttrType.DOUBLE))
        return StreamFunctionStage(ref, attrs, fn)

    ext = lookup("stream_function", name) or lookup(
        "stream_processor", name
    )
    if ext is not None:
        params = [compile_expression(p, scope) for p in handler.parameters]
        return ext(params, schema_attrs, ref, scope)

    raise SiddhiAppCreationError(f"unknown stream function '#{name}'")


# ---------------------------------------------------------------------------
# script functions: define function f[python] return type { body }
# ---------------------------------------------------------------------------


def make_script_function(fdef):
    """Compile a `define function` body into an expression-compiler factory
    (reference: FunctionDefinition + script executors; the reference ships
    JavaScript/R/Scala via extensions — here the language is python, traced
    straight into the device program, so bodies must be jnp-compatible
    numeric/bool expressions over `data`)."""
    import textwrap

    lang = fdef.language.lower()
    if lang not in ("python", "py"):
        raise SiddhiAppCreationError(
            f"function '{fdef.id}': unsupported script language "
            f"'{fdef.language}' (python is built in)"
        )
    body = textwrap.dedent(fdef.body).strip()
    if "return" not in body:
        body = f"return {body}"
    src = "def __fn__(data):\n" + textwrap.indent(body, "    ")
    ns: dict = {}
    exec(src, {"jnp": jnp, "np": __import__("numpy")}, ns)
    raw = ns["__fn__"]
    rt = fdef.return_type

    def factory(params: list[CompiledExpr], scope: Scope) -> CompiledExpr:
        def fn(env: Env) -> jnp.ndarray:
            vals = [p(env) for p in params]
            return jnp.asarray(raw(vals)).astype(PHYSICAL_DTYPE[rt])

        return CompiledExpr(rt, fn)

    return factory
