"""Pattern/sequence query runtime: NFA token table + selector as one jitted step.

Reference analog: the per-query object graph built by
util/parser/StateInputStreamParser.java + QueryParser.java for state streams,
with Pattern*ProcessStreamReceiver per input stream. Here each input stream gets
its own jitted step `(state, batch, now) -> (state', out, aux)` sharing the same
token-table state; TIMER delivery for absent states is a third step variant.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax
import jax.lax as lax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.event import EventBatch, KIND_TIMER, StreamSchema
from siddhi_tpu.core.flow import Flow
from siddhi_tpu.core.pattern import NO_TIMER, PatternProgram
from siddhi_tpu.core.query_runtime import BaseQueryRuntime
from siddhi_tpu.core.selector import CompiledSelector
from siddhi_tpu.core.types import InternTable
from siddhi_tpu.query_api.execution import Query, StateInputStream


# tuning hook (tools/exp_count.py): overrides the count-kernel chunk size
COUNT_CHUNK_OVERRIDE: Optional[int] = None


class PatternQueryRuntime(BaseQueryRuntime):
    def __init__(
        self,
        query: Query,
        query_id: str,
        schemas: dict[str, StreamSchema],
        interner: InternTable,
        group_capacity: Optional[int] = None,
        token_capacity: int = 128,
        count_capacity: int = 8,
        batch_size: int = 64,
        tables: Optional[dict] = None,
        pattern_chunk: Optional[int] = None,
    ):
        self._pattern_chunk = pattern_chunk
        self.query = query
        self.query_id = query_id
        state_stream = query.input_stream
        assert isinstance(state_stream, StateInputStream)
        self.prog = PatternProgram(
            state_stream,
            schemas,
            interner,
            token_capacity=token_capacity,
            count_capacity=count_capacity,
        )
        # selector/having `in <table>` conditions resolve against these
        # (pattern node filters are compiled before tables attach — the
        # reference allows them there too; that lands with the NFA env rework)
        for t in (tables or {}).values():
            self.prog.scope.add_table(t)
        # emission buffer scales with the token table: every pending token can
        # emit on one event, so raising @app:patternCapacity raises this too
        self.out_cap = max(batch_size, 64, token_capacity)

        # select * over a pattern exposes every ref's attributes in order
        # (duplicate names require explicit projection)
        flat_attrs = []
        seen = set()
        dup = set()
        for a in self.prog.refs:
            for name, t in schemas[a.stream_id].attrs:
                if name in seen:
                    dup.add(name)
                else:
                    seen.add(name)
                    flat_attrs.append((name, t))
        if query.selector.select_all and dup:
            raise SiddhiAppCreationError(
                f"select * over this pattern is ambiguous for {sorted(dup)}; "
                "project explicitly"
            )
        # the selector resolves against a CHILD scope so its key set is known
        # exactly — those keys (plus cross-ref condition reads) are the only
        # capture lanes the token table / emission buffer materialize
        # (PatternProgram.capture_keep)
        sel_scope = self.prog.scope.child()
        self.selector = CompiledSelector(
            query.selector,
            sel_scope,
            flat_attrs,
            batch_mode=False,
            group_capacity=group_capacity,
        )
        self._sel_used_keys = frozenset(sel_scope.used_keys)
        self.prog.set_capture_readers(self._sel_used_keys)
        self._setup_output(query, query_id)
        self._attach_tables(tables, interner)
        self._scope = self.prog.scope
        self.needs_scheduler = self.prog.needs_scheduler
        self.timer_target = None
        self._steps = {
            sid: jax.jit(self._make_step(sid), donate_argnums=(0,))
            for sid in self.prog.stream_ids
        }
        self._timer_step = jax.jit(self._make_step(None), donate_argnums=(0,))

    def arm_lineage(self, cfg) -> None:
        """Enable provenance recording (@app:lineage): force every ref's
        captured-timestamp lane to materialize (the emission buffer then
        carries, per match, exactly which input row filled each linearized
        slot) and surface them as `__lin.*` lanes feeding a
        PatternQueryLineage. Must run before anything traces the steps
        (capture projection memoizes at first trace); emissions are
        untouched."""
        from siddhi_tpu.core.executor import TS_ATTR
        from siddhi_tpu.observability.lineage import PatternQueryLineage

        keys = set(self._sel_used_keys)
        keys |= {(a.ref, None, TS_ATTR) for a in self.prog.refs}
        self.prog.set_capture_readers(frozenset(keys))
        self.lineage = PatternQueryLineage(
            cfg, self.query_id, self._published_kinds(),
            refs=[(a.ref, a.stream_id) for a in self.prog.refs],
        )

    # ---- device program --------------------------------------------------

    def init_state(self, now: int = 0):
        return {
            "tok": self.prog.init_state(now),
            "sel": self.selector.init_state(),
            # max TIMER timestamp already processed: next_timer never re-arms
            # a deadline at or before this (a logical-and element whose absent
            # deadline passed but whose present side is still pending would
            # otherwise re-arm the same past deadline forever)
            "timer_ts": jnp.full((), -(1 << 62), jnp.int64),
        }

    def _make_step(self, stream_id: Optional[str]):
        prog = self.prog
        from siddhi_tpu.core import pattern as pattern_mod

        kernel = None
        chunk = None
        if stream_id is not None and not pattern_mod.FORCE_SCAN:
            if prog.fast_path_ok:
                # chunks no larger than half the token table, so a chunk's
                # fork demand can always be met by lanes freed previously
                kernel, chunk = prog.apply_batch_fast, max(1, prog.T // 2)
            elif prog.count_fast_ok:
                # chunk = T*min_count keeps the no-spurious-overflow bound
                # (arming demand per chunk <= chunk/min <= T lanes) while
                # amortizing the per-chunk [B]-shaped fixed cost — bigger
                # chunks cut the kernel's gather/scatter element traffic per
                # event, the TPU wall (scalar-core, ~1 element/cycle).
                # @app:patternChunk overrides for workloads whose match rate
                # is known to be low (overflow still detected + warned).
                m0 = max(1, prog.slots[0].min_count)
                kernel = prog.apply_batch_count
                chunk = (
                    COUNT_CHUNK_OVERRIDE
                    or self._pattern_chunk
                    or max(1, prog.T * m0)
                )

        if kernel is not None:
            ker, C0 = kernel, chunk

            def fast_step(state, tstates, batch: EventBatch, now):
                out0 = prog.init_out(self.out_cap)
                B = batch.capacity
                # chunk so completed tokens free their lanes BETWEEN chunks:
                # per-chunk fork pressure is bounded by the chunk size, which
                # approximates the scan path's per-event lane recycling;
                # pad (valid=False) rather than shrink chunks so odd batch
                # sizes keep the wide vectorized shape
                C = min(B, C0)
                pad = (-B) % C
                if pad:
                    def padded(x, fill=0):
                        return jnp.concatenate(
                            [x, jnp.full((pad,) + x.shape[1:], fill, x.dtype)]
                        )

                    batch = EventBatch(
                        ts=padded(batch.ts),
                        kind=padded(batch.kind),
                        valid=padded(batch.valid, False),
                        cols={n: padded(c) for n, c in batch.cols.items()},
                    )
                    B = B + pad

                def chunk_body(carry, xs):
                    tok, out, out_n, ovf = carry
                    tok, out, out_n, ovf = ker(
                        tok, xs["ts"], xs["kind"], xs["valid"],
                        {stream_id: {n: xs[f"c.{n}"] for n in batch.cols}},
                        out, out_n, ovf, now,
                    )
                    return (tok, out, out_n, ovf), None

                xs = {
                    "ts": batch.ts.reshape(B // C, C),
                    "kind": batch.kind.reshape(B // C, C),
                    "valid": batch.valid.reshape(B // C, C),
                    **{
                        f"c.{n}": c.reshape(B // C, C)
                        for n, c in batch.cols.items()
                    },
                }
                (tok, out, _n, ovf), _ = lax.scan(
                    chunk_body,
                    (state["tok"], out0, np.int32(0), np.bool_(False)),
                    xs,
                )
                # fast-path patterns have no waiting atoms -> no timers
                return self._finish_step(
                    state, tok, out, ovf, tstates, now, state["timer_ts"],
                    in_batch=batch,
                )

            return fast_step

        def step(state, tstates, batch: EventBatch, now):
            out0 = prog.init_out(self.out_cap)
            carry0 = (
                state["tok"],
                out0,
                np.int32(0),
                np.bool_(False),
            )
            xs = {
                "ts": batch.ts,
                "kind": batch.kind,
                "valid": batch.valid,
                **{f"c.{n}": c for n, c in batch.cols.items()},
            }

            def body(carry, row):
                tok, out, out_n, ovf = carry
                stream_cols = (
                    {
                        stream_id: {
                            n: row[f"c.{n}"] for n in batch.cols
                        }
                    }
                    if stream_id is not None
                    else {}
                )
                tok, out, out_n, ovf = prog.apply_event(
                    tok,
                    row["ts"],
                    row["kind"],
                    row["valid"],
                    stream_cols,
                    out,
                    out_n,
                    ovf,
                    timer_seen=state["timer_ts"],
                )
                return (tok, out, out_n, ovf), None

            (tok, out, _, ovf), _ = lax.scan(body, carry0, xs)
            timer_rows = batch.valid & (batch.kind == KIND_TIMER)
            timer_ts = jnp.maximum(
                state["timer_ts"],
                jnp.max(
                    jnp.where(timer_rows, batch.ts, -(np.int64(1) << 62))
                ),
            )
            return self._finish_step(
                state, tok, out, ovf, tstates, now, timer_ts, in_batch=batch
            )

        return step

    def _finish_step(
        self, state, tok, out, ovf, tstates, now, timer_ts, in_batch=None
    ):
        """Shared step tail: emission buffer -> selector -> table op -> aux."""
        prog = self.prog
        emit_batch = EventBatch(
            ts=out["ts"],
            kind=jnp.zeros_like(out["ts"], dtype=jnp.int8),
            valid=out["valid"],
            cols={},
        )
        flow = Flow(
            batch=emit_batch,
            ref=prog.refs[0].ref,
            now=now,
            extra_cols=prog.out_env_cols(out),
            tables=tstates,
        )
        sel_state, out_batch = self.selector.apply(state["sel"], flow)
        if self.table_op is not None:
            tstates = self.table_op(tstates, out_batch, now, flow.aux)
        aux = dict(flow.aux)
        aux["pattern_overflow"] = ovf
        aux["next_timer"] = prog.next_timer(tok, after=timer_ts)
        if self.lineage is not None:
            # provenance lanes: the emission buffer's per-ref capture
            # timestamps (arm_lineage forced every ts lane to materialize)
            # — extra program outputs only, emissions untouched
            from siddhi_tpu.core.event import KIND_CURRENT
            from siddhi_tpu.observability.lineage import LIN

            aux[LIN + "out_valid"] = out_batch.valid
            aux[LIN + "out_kind"] = out_batch.kind
            aux[LIN + "out_ts"] = out_batch.ts
            for i, _a in enumerate(prog.refs):
                aux[f"{LIN}p_n{i}"] = out[f"n{i}"]
                tsr = out.get(f"ts{i}")
                if tsr is not None:
                    aux[f"{LIN}p_ts{i}"] = tsr
            if in_batch is not None:
                aux[LIN + "in"] = in_batch.valid & (
                    in_batch.kind == KIND_CURRENT
                )
                aux[LIN + "in_ts"] = in_batch.ts
        return (
            {"tok": tok, "sel": sel_state, "timer_ts": timer_ts},
            tstates,
            out_batch,
            aux,
        )

    # ---- host side -------------------------------------------------------

    def receive(self, batch: EventBatch, now: int, stream_id: str):
        with self._receive_lock:
            if self.state is None:
                self.state = self._fresh(self.init_state(now))
            step = self._steps[stream_id]
            tstates = self._collect_table_states()
            timed = self._need_step_clock()
            if timed:
                import time as _time

                t0 = _time.perf_counter_ns()
            self.state, tstates, out, aux = step(
                self.state, tstates, batch, jnp.asarray(now, dtype=jnp.int64)
            )
            if timed:
                # one jitted program per pattern stream: the telemetry
                # component embeds the stream id (see _observe_step)
                self._observe_step(
                    step, (stream_id, int(batch.ts.shape[0])),
                    _time.perf_counter_ns() - t0,
                )
            self._writeback_table_states(tstates)
            lin = self.lineage
            if lin is not None:
                # under the receive lock: recorder order == dispatch order
                aux = self._lin_observe(lin, aux, now, tag=stream_id)
        self._warn_aux(aux)
        return out, aux

    def receive_timer(self, schema_batch: EventBatch, t_ms: int):
        with self._receive_lock:
            if self.state is None:
                self.state = self._fresh(self.init_state(t_ms))
            tstates = self._collect_table_states()
            self.state, tstates, out, aux = self._timer_step(
                self.state, tstates, schema_batch, jnp.asarray(t_ms, dtype=jnp.int64)
            )
            self._writeback_table_states(tstates)
            lin = self.lineage
            if lin is not None:
                aux = self._lin_observe(lin, aux, t_ms, tag=None)
        self._warn_aux(aux)
        return out, aux

    def describe_state(self) -> dict:
        """NFA introspection: active state-machine instances per linearized
        slot (the device token table's `active`/`slot` lanes pulled to host)
        plus the earliest pending within/absent deadline."""
        d = super().describe_state()
        prog = self.prog
        d["token_capacity"] = prog.T
        slots = []
        for s in prog.slots:
            slots.append({
                "refs": [a.ref for a in s.atoms],
                "absent": s.is_absent,
                "count": [s.min_count, s.max_count] if s.is_count else None,
            })
        from siddhi_tpu.observability.introspect import device_reads_ok

        if self.state is None:
            d["states"] = [dict(s, active=0) for s in slots]
            return d
        if not device_reads_ok():
            # degraded relay: one d2h would poison dispatch
            d["states"] = [dict(s, active=None) for s in slots]
            return d
        try:
            with self._receive_lock:
                tok = self.state["tok"]
                active = np.asarray(tok["active"])
                slot = np.asarray(tok["slot"])
                deadline = int(
                    np.asarray(
                        prog.next_timer(tok, after=self.state["timer_ts"])
                    )
                )
        except Exception:
            # a concurrent donated-state dispatch (fused ingest) can delete
            # the buffers under us; introspection degrades, never raises
            d["states"] = [dict(s, active=None) for s in slots]
            return d
        per_state = np.bincount(slot[active], minlength=len(slots))
        d["states"] = [
            dict(s, active=int(per_state[i])) for i, s in enumerate(slots)
        ]
        d["active_instances"] = int(active.sum())
        d["next_deadline_ms"] = deadline if deadline < int(NO_TIMER) else None
        return d

    def prime(self, now: int) -> dict:
        """Arm the initial token's clock (absent-at-start patterns need a timer
        before any event arrives — reference:
        AbsentStreamPreStateProcessor.start scheduling)."""
        with self._receive_lock:
            if self.state is None:
                self.state = self._fresh(self.init_state(now))
            t = self.prog.next_timer(
                self.state["tok"], after=self.state["timer_ts"]
            )
        return {"next_timer": t}
