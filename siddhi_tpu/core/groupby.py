"""Compiled group-by: key generation + persistent slot table.

Reference: query/selector/GroupByKeyGenerator.java builds a string key per
event; QuerySelector.java:167-226 keeps per-key aggregator state in maps keyed
by that string. Here the key is an int64 device column, the map is a
fixed-capacity device key table (ops/group.py:assign_slots), and aggregator
state is a [G]-array slice per aggregator.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax.numpy as jnp

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.executor import CompiledExpr, Env, Scope, compile_expression
from siddhi_tpu.core.types import AttrType
from siddhi_tpu.ops.group import SortedGroups, assign_slots, mix_keys
from siddhi_tpu.query_api.expression import Variable

DEFAULT_GROUP_CAPACITY = 1024


def _as_key_col(col: jnp.ndarray, t: AttrType) -> jnp.ndarray:
    """Integer-encode one key column (floats are bitcast so distinct payloads
    stay distinct; strings are already interned ids)."""
    if t in (AttrType.FLOAT, AttrType.DOUBLE):
        return jnp.asarray(col).view(jnp.int32).astype(jnp.int64)
    return col.astype(jnp.int64)


@dataclasses.dataclass
class GroupCtx:
    """Per-batch group context handed to aggregators via FlowInfo."""

    slot: jnp.ndarray    # [B] int32; == capacity for non-keyed rows
    key: jnp.ndarray     # [B] int64
    sorted: SortedGroups  # lexsorted (era, key) view for segmented reductions
    capacity: int
    key_of: Callable[[Env], jnp.ndarray]  # env -> int64 key column (any length)
    overflow: jnp.ndarray = None  # scalar bool


class CompiledGroupBy:
    def __init__(
        self,
        group_by: list[Variable],
        scope: Scope,
        capacity: int = DEFAULT_GROUP_CAPACITY,
    ):
        if not group_by:
            raise SiddhiAppCreationError("empty group by")
        self.capacity = int(capacity)
        self.keys: list[CompiledExpr] = [
            compile_expression(v, scope) for v in group_by
        ]
        for v, c in zip(group_by, self.keys):
            if c.type is AttrType.OBJECT:
                raise SiddhiAppCreationError(
                    f"cannot group by OBJECT attribute '{v.attribute}'"
                )

    def key_of(self, env: Env) -> jnp.ndarray:
        return mix_keys([_as_key_col(c(env), c.type) for c in self.keys])

    def init_state(self):
        g = self.capacity
        return {
            "keys": jnp.zeros((g,), jnp.int64),
            "used": jnp.zeros((g,), jnp.bool_),
            "n": jnp.zeros((), jnp.int32),
        }

    def assign(self, state, env: Env, active: jnp.ndarray, reset: jnp.ndarray = None):
        bk = self.key_of(env)
        keys, used, n, slot, grp, overflow = assign_slots(
            state["keys"], state["used"], state["n"], bk, active, reset=reset
        )
        ctx = GroupCtx(
            slot=slot, key=bk, sorted=grp, capacity=self.capacity,
            key_of=self.key_of, overflow=overflow,
        )
        return {"keys": keys, "used": used, "n": n}, ctx
