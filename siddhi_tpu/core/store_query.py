"""Store queries: one-shot pull queries over tables (and, later, named windows
and aggregations).

Reference: util/parser/StoreQueryParser.java:79-491 compiling Find/Select/
Update/Delete store-query runtimes, cached per query string by
SiddhiAppRuntime.java:272-299. Here the whole pull — order table rows, apply
the on-condition, run the selector, apply any table write-back — is one jitted
device program over the live table state.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from siddhi_tpu.core.errors import (
    DefinitionNotExistError,
    SiddhiAppCreationError,
    SiddhiAppRuntimeError,
)
from siddhi_tpu.core.event import Event, EventBatch, StreamSchema
from siddhi_tpu.core.executor import Scope, compile_expression
from siddhi_tpu.core.flow import Flow
from siddhi_tpu.core.selector import CompiledSelector
from siddhi_tpu.core.table import compile_table_output
from siddhi_tpu.core.types import AttrType
from siddhi_tpu.query_api.execution import StoreQuery

_MAX64 = jnp.iinfo(jnp.int64).max


class StoreQueryRuntime:
    """Compiled pull query over one table source."""

    def __init__(
        self,
        sq: StoreQuery,
        tables: dict,
        interner,
        group_capacity=None,
        windows: dict | None = None,
        aggregations: dict | None = None,
    ):
        store = sq.input_store
        self._sq = sq
        self.no_from = store is None
        if self.no_from and sq.output_stream is None:
            raise SiddhiAppCreationError(
                "a store query needs a 'from <store>' clause or an "
                "insert/update/delete output"
            )
        windows = windows or {}
        aggregations = aggregations or {}

        self.aggregation = (
            aggregations.get(store.store_id) if store is not None else None
        )
        self.is_agg = self.aggregation is not None
        self.per = None
        self.within = None
        if self.is_agg:
            from siddhi_tpu.core.aggregation import parse_per, parse_within_value
            from siddhi_tpu.query_api.expression import Constant

            if store.per is None:
                raise SiddhiAppCreationError(
                    "aggregation store queries need a per '<duration>' clause"
                )
            if not isinstance(store.per, Constant):
                raise SiddhiAppCreationError("'per' must be a constant duration")
            self.per = parse_per(store.per.value)
            if store.within is not None:
                w1, w2 = store.within
                if not isinstance(w1, Constant) or (
                    w2 is not None and not isinstance(w2, Constant)
                ):
                    raise SiddhiAppCreationError("'within' operands must be constants")
                if w2 is None:
                    self.within = parse_within_value(w1.value)
                else:
                    self.within = (
                        parse_within_value(w1.value)[0],
                        parse_within_value(w2.value)[0],
                    )
                if self.within[0] >= self.within[1]:
                    # reference: StoreQueryCreationException when the within
                    # range is empty/inverted
                    raise SiddhiAppCreationError(
                        "'within' start time must be before the end time"
                    )
            source_schema = self.aggregation.out_schema
            table = self.aggregation
        elif self.no_from:
            # `select <constants> insert into T;` — one synthetic row
            # (reference: InsertStoreQueryRuntime)
            table = None
            source_schema = StreamSchema("__const__", [])
        else:
            table = tables.get(store.store_id) or windows.get(store.store_id)
            if table is None:
                raise DefinitionNotExistError(
                    f"'{store.store_id}' is not a defined table, window, "
                    "or aggregation"
                )
            if store.within is not None or store.per is not None:
                raise SiddhiAppCreationError(
                    "'within'/'per' apply to aggregation store queries"
                )
            source_schema = table.schema
        self.table = table  # findable source: table, window, or aggregation
        self.is_window = store is not None and store.store_id in windows
        self.tables = dict(tables)
        self.ref = (store.alias or store.store_id) if store is not None else "__const__"

        scope = Scope(interner)
        scope.add_stream(self.ref, source_schema.attr_types)
        scope.default_ref = self.ref
        for t in self.tables.values():
            scope.add_table(t)

        self.on = None
        if store is not None and store.on is not None:
            self.on = compile_expression(store.on, scope)
            if self.on.type is not AttrType.BOOL:
                raise SiddhiAppCreationError("'on' must be a boolean expression")

        self.selector = CompiledSelector(
            sq.selector,
            scope,
            input_attrs=source_schema.attrs,
            batch_mode=True,  # one row per group key (store queries pull once)
            group_capacity=group_capacity,
        )
        # plain aggregation (no group by) collapses to the final running row
        # (reference: SelectStoreQueryRuntime with aggregating selector)
        self.agg_single = bool(self.selector.aggregators) and self.selector.group is None
        self.out_schema = StreamSchema(f"__sq_{self.ref}", self.selector.out_attrs)
        self.interner = interner

        self._write_target = getattr(sq.output_stream, "target", None)
        if sq.output_stream is not None and self._write_target not in self.tables:
            # a store query has no stream junctions: its insert/update/delete
            # target MUST be a defined table (reference: StoreQueryParser
            # resolves the target against the table map and fails otherwise)
            raise DefinitionNotExistError(
                f"store query target '{self._write_target}' is not a "
                "defined table"
            )
        self.table_op = (
            compile_table_output(sq.output_stream, self.out_schema, self.tables, interner)
            if sq.output_stream is not None
            else None
        )
        self._step = jax.jit(self._step_impl)

    # ---- device program --------------------------------------------------

    def _step_impl(self, tstates, now, agg_batch: EventBatch | None = None):
        if agg_batch is not None:
            batch = agg_batch
        elif self.no_from:
            batch = EventBatch(
                ts=jnp.full((1,), now, jnp.int64),
                kind=jnp.zeros((1,), jnp.int8),
                valid=jnp.ones((1,), jnp.bool_),
                cols={},
            )
        else:
            st = tstates[self.table.table_id]
            if self.is_window:
                # named window: view() already yields insertion order
                cols, ts, mask = self.table.view(st)
                batch = EventBatch(
                    ts=ts, kind=jnp.zeros_like(ts, dtype=jnp.int8),
                    valid=mask, cols=cols,
                )
            else:
                # iterate in insertion order (reference: holder iteration order)
                order = jnp.argsort(jnp.where(st["valid"], st["seq"], _MAX64))
                batch = EventBatch(
                    ts=st["ts"][order],
                    kind=jnp.zeros_like(st["ts"], dtype=jnp.int8),
                    valid=st["valid"][order],
                    cols={n: c[order] for n, c in st["cols"].items()},
                )
        flow = Flow(batch=batch, ref=self.ref, now=now, tables=tstates)
        if self.on is not None:
            mask = self.on(flow.env())
            batch = EventBatch(batch.ts, batch.kind, batch.valid & mask, batch.cols)
            flow = dataclasses.replace(flow, batch=batch)
        out_state, out = self.selector.apply(self.selector.init_state(), flow)
        if self.agg_single:
            idx = jnp.arange(out.valid.shape[0])
            last = jnp.max(jnp.where(out.valid, idx, -1))
            out = EventBatch(
                out.ts, out.kind, out.valid & (idx == last), out.cols
            )
        aux = dict(flow.aux)
        if self.table_op is not None:
            tstates = self.table_op(tstates, out, now, aux)
        return tstates, out

    # ---- host side -------------------------------------------------------

    def execute(self, now: int) -> list[Event]:
        tstates = {tid: t.state for tid, t in self.tables.items()}
        for tid, t in self.tables.items():
            if getattr(t, "lazy", False):
                # queryable lazy store: push the on-condition down and stage
                # only the matching rows (the device re-applies the condition)
                on = self._sq.input_store.on if (
                    self._sq.input_store is not None
                    and self._sq.input_store.store_id == tid
                ) else None
                rows = t.record_store.query(on, self.interner)
                if rows is None:
                    # a per-execution failure, not a deployment error
                    # (reference: StoreQuery runtime exceptions)
                    raise SiddhiAppRuntimeError(
                        f"table '{tid}': lazy record store did not push the "
                        "condition down (query() returned None)"
                    )
                if len(rows) > t.capacity:
                    raise SiddhiAppRuntimeError(
                        f"table '{tid}': pushdown returned {len(rows)} rows "
                        f"but capacity is {t.capacity}; narrow the condition "
                        "or raise @capacity(size='N')"
                    )
                st = t.init_state()
                if rows:
                    batch = t.schema.to_batch(
                        [0] * len(rows), rows, self.interner,
                        capacity=len(rows),
                    )
                    st = t.insert(st, batch, {})
                tstates[tid] = st
        if self.is_agg:
            batch = self.table.find(self.per, self.within, now)
            if not hasattr(self, "_agg_step"):
                self._agg_step = jax.jit(
                    lambda ts_, b, n: self._step_impl(ts_, n, agg_batch=b)
                )
            tstates, out = self._agg_step(
                tstates, batch, jnp.asarray(now, dtype=jnp.int64)
            )
        else:
            if self.is_window:
                tstates[self.table.table_id] = self.table.state
            tstates, out = self._step(tstates, jnp.asarray(now, dtype=jnp.int64))
        for tid, t in self.tables.items():
            if getattr(t, "lazy", False):
                continue  # staged pushdown subsets never become live state
            t.state = tstates[tid]  # windows are read-only: not written back
        if self.table_op is not None and self._write_target in self.tables:
            self.tables[self._write_target].notify_change()
        rows = self.out_schema.from_batch(out, self.interner)
        return [Event(ts, data) for ts, _kind, data in rows]
