"""Window processors — device-resident ring buffers with batched emission.

Reference: query/processor/stream/window/*.java (17 built-ins). The reference
mutates per-event queues inside synchronized blocks; here each window is a pure
stage over the Flow with a fixed-capacity slot-indexed ring as carried state, and
the interleaved CURRENT/EXPIRED/RESET emission order of the reference is
reproduced by assigning every candidate output event a sort key
(trigger_row, kind, seq) and lexsorting — one vectorized program, no per-event
control flow.

Emission-order contracts reproduced (validated against the reference sources):
- length: per arrival when full, evictee EXPIRED emitted before the CURRENT
  (LengthWindowProcessor.java:102-138 insertBeforeCurrent)
- time/externalTime: all due EXPIREDs flush before the triggering CURRENT
  (TimeWindowProcessor.java:79+)
- lengthBatch/timeBatch: on flush, prev-batch EXPIREDs, then RESET, then the
  bucket's CURRENTs (LengthBatchWindowProcessor.java:108-160)
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.event import (
    EventBatch,
    KIND_CURRENT,
    KIND_EXPIRED,
    KIND_RESET,
    KIND_TIMER,
    StreamSchema,
)
from siddhi_tpu.core.executor import Env, Scope, TS_ATTR, compile_expression
from siddhi_tpu.ops.prefix import cummax as _cummax
from siddhi_tpu.ops.group import permute_by as _permute_by
from siddhi_tpu.ops.scatter import compact_set_at as _compact_set_at, set_at as _set_at
from siddhi_tpu.core.flow import Flow
from siddhi_tpu.core.types import AttrType
from siddhi_tpu.query_api.definition import WindowSpec
from siddhi_tpu.query_api.expression import Constant

BIG = jnp.iinfo(jnp.int32).max
NO_TIMER = jnp.iinfo(jnp.int64).max

DEFAULT_TIME_CAPACITY = 1024


def _const_raw(spec: WindowSpec, i: int, what: str):
    if i >= len(spec.parameters) or not isinstance(spec.parameters[i], Constant):
        raise SiddhiAppCreationError(f"window {spec.name}: parameter {i} must be a constant {what}")
    return spec.parameters[i].value


def _const_param(spec: WindowSpec, i: int, what: str) -> int:
    return int(_const_raw(spec, i, what))


class WindowStage:
    """Base: (state, Flow) -> (state', Flow') with out-capacity growth."""

    needs_scheduler = False
    # tumbling windows flip the selector into batch group-by output mode
    # (reference: QueryParser batchProcessingAllowed -> QuerySelector)
    is_batch = False
    # cron-driven windows schedule fire times host-side (CronSchedule)
    cron_schedule = None

    def init_state(self):
        raise NotImplementedError

    def apply(self, state, flow: Flow):
        raise NotImplementedError

    def view(self, state):
        """Stored window contents for probing: `(cols, ts, mask)` with rows in
        insertion order (reference: FindableProcessor.find iterating the window
        buffer, query/processor/stream/window/LengthWindowProcessor.java:144)."""
        raise NotImplementedError(f"{type(self).__name__} is not findable")

    def share_signature(self):
        """Canonical runtime identity for cross-query state sharing
        (core/fusion_exec.py `_chain_share_key`): two window stages whose
        signatures are equal and non-None hold byte-identical device state
        under identical input, so one ring/bucket can serve both. The base
        answer is None (never share) — only the plain ring (SlidingWindow)
        and bucket (BatchWindow) shapes opt in; exotic windows (sort,
        frequent, cron, ...) carry parameters this tuple cannot see."""
        return None

    def view_seq(self, state):
        """Per-slot window admission seq ids, permuted like `view()` (the
        SlidingWindow monotone `seq` lane; -1 = empty slot). None when this
        window type tracks no admission order — join lineage then records
        the partner as unresolved (observability/lineage.py)."""
        return None

    def describe_state(self, state) -> dict:
        """Introspection snapshot of the live buffer: type, fill, capacity,
        oldest/newest stored timestamps. Pull-only (one host read per call);
        rides `view()` so every findable window gets it for free."""
        import numpy as np

        d: dict = {"type": type(self).__name__}
        cap = getattr(self, "w", None)
        if cap is not None:
            d["capacity"] = int(cap)
        dur = getattr(self, "t", None)
        if dur is not None:
            d["duration_ms"] = int(dur)
        from siddhi_tpu.observability.introspect import device_reads_ok

        if not device_reads_ok():
            d["fill"] = None  # degraded relay: one d2h would poison dispatch
            return d
        try:
            _cols, ts, mask = self.view(state)
            m = np.asarray(mask)
        except NotImplementedError:
            return d
        except Exception:
            # a concurrent donated-state dispatch (fused ingest) can delete
            # the buffers under us; introspection degrades, never raises
            d["fill"] = None
            return d
        fill = int(m.sum())
        d["fill"] = fill
        if fill:
            lived = np.asarray(ts)[m]
            d["oldest_ts"] = int(lived.min())
            d["newest_ts"] = int(lived.max())
        return d


# ---------------------------------------------------------------------------
# sliding family: length / time / timeLength / externalTime / delay
# ---------------------------------------------------------------------------


class SlidingWindow(WindowStage):
    """Generic ring: capacity W (always length-evicts at W) plus optional time
    predicate over a per-event 'window time' (event ts, or an attribute for
    externalTime). Covers length(N) [W=N], time(T), timeLength(T, N),
    externalTime(tsAttr, T).

    Overflow policy for time windows: if more than W events are simultaneously
    live, the oldest are evicted EARLY — they are still emitted as EXPIRED (the
    capacity eviction rides the same candidate path), so downstream aggregates
    stay exactly consistent; only the expiry *time* is early. The reference has
    no such bound (unbounded Java queues); raise DEFAULT_TIME_CAPACITY or the
    per-window capacity if early expiry is observed."""

    def __init__(
        self,
        schema: StreamSchema,
        ref: str,
        capacity: int,
        duration_ms: Optional[int] = None,
        time_attr: Optional[str] = None,
        use_scheduler: bool = False,
    ):
        self.schema = schema
        self.ref = ref
        self.w = int(capacity)
        self.t = duration_ms
        self.time_attr = time_attr
        self.needs_scheduler = use_scheduler

    def share_signature(self):
        if self.needs_scheduler:
            return None  # timer-armed: host scheduling owns per-query state
        return (
            "SlidingWindow", self.w, self.t, self.time_attr,
        )

    def init_state(self):
        w = self.w
        return {
            "cols": {n: jnp.zeros((w,), a.dtype) for n, a in self.schema.empty_batch(1).cols.items()},
            "ts": jnp.zeros((w,), jnp.int64),
            "wts": jnp.zeros((w,), jnp.int64),
            "seq": jnp.full((w,), -1, jnp.int64),
            "total": jnp.zeros((), jnp.int64),
        }

    def apply(self, state, flow: Flow):
        b = flow.batch
        bsz = b.capacity
        w = self.w
        k = w + bsz
        total = state["total"]

        valid_cur = b.valid & (b.kind == KIND_CURRENT)
        is_timer = b.valid & (b.kind == KIND_TIMER)
        # window-time of each batch row
        if self.time_attr is not None:
            bwts = b.cols[self.time_attr].astype(jnp.int64)
        else:
            bwts = b.ts
        rank = jnp.cumsum(valid_cur.astype(jnp.int32)) - valid_cur.astype(jnp.int32)
        c = valid_cur.sum(dtype=jnp.int32)
        seq_batch = jnp.where(valid_cur, total + rank, np.int64(-1))

        # element view: ring slots then batch rows
        elem_ts = jnp.concatenate([state["ts"], b.ts])
        elem_wts = jnp.concatenate([state["wts"], bwts])
        elem_seq = jnp.concatenate([state["seq"], seq_batch])
        elem_cols = {
            n: jnp.concatenate([state["cols"][n], b.cols[n]]) for n in b.cols
        }
        present = elem_seq >= 0
        own_row = jnp.concatenate(
            [jnp.full((w,), -1, jnp.int32), jnp.arange(bsz, dtype=jnp.int32)]
        )

        # --- eviction triggers ---
        # capacity/length: evicted by the insertion of seq_e + W
        trig_rank = (elem_seq + w - total).astype(jnp.int32)
        len_trig_valid = present & (trig_rank >= 0) & (trig_rank < c)
        perm = jnp.argsort(~valid_cur, stable=True).astype(jnp.int32)  # rank -> row
        trig_row_len = jnp.where(
            len_trig_valid, perm[jnp.clip(trig_rank, 0, bsz - 1)], BIG
        )

        if self.t is None:
            # Pure length window: deaths pair 1:1 with insertions (the
            # insertion of seq_e + W evicts seq_e), so the EXPIRED/CURRENT
            # interleaving is pure rank arithmetic — no candidate lexsort
            # (reference behavior: LengthWindowProcessor.java emits the
            # displaced event then the arriving one, per event).
            return self._apply_length(
                state, flow, b, bsz, w, total, valid_cur, bwts, rank, c,
                seq_batch, elem_ts, elem_cols, present,
                trig_rank, len_trig_valid, perm,
            )

        trigger_ok = valid_cur | is_timer
        due = (
            trigger_ok[None, :]
            & present[:, None]
            & (bwts[None, :] - elem_wts[:, None] >= self.t)
            & (jnp.arange(bsz, dtype=jnp.int32)[None, :] >= own_row[:, None])
        )
        has_time_trig = due.any(axis=1)
        trig_row_time = jnp.where(has_time_trig, jnp.argmax(due, axis=1).astype(jnp.int32), BIG)

        trig_row = jnp.minimum(trig_row_len, trig_row_time)
        evict = present & (trig_row < BIG)

        # --- candidate assembly: K expired + B current candidates ---
        death_key = jnp.where(evict, trig_row * 2, BIG)
        birth_key = jnp.where(own_row >= 0, own_row * 2 + 1, -1)

        cand_key = jnp.concatenate(
            [death_key, jnp.where(valid_cur, jnp.arange(bsz, dtype=jnp.int32) * 2 + 1, BIG)]
        )
        cand_elem = jnp.concatenate(
            [jnp.arange(k, dtype=jnp.int32), jnp.arange(w, k, dtype=jnp.int32)]
        )
        cand_is_exp = jnp.concatenate(
            [jnp.ones((k,), bool), jnp.zeros((bsz,), bool)]
        )
        cand_valid = jnp.concatenate([evict, valid_cur])
        cand_seq = elem_seq[cand_elem]

        order = jnp.lexsort((cand_seq, jnp.where(cand_valid, cand_key, BIG)))
        out_n = k + bsz
        o_elem = cand_elem[order]
        o_exp = cand_is_exp[order]
        o_valid = cand_valid[order]
        o_key = jnp.where(o_valid, cand_key[order], BIG)

        trigger_ts = b.ts  # trigger row's event ts stands in for "currentTime"
        o_trig_row = jnp.clip(o_key // 2, 0, bsz - 1)
        out = EventBatch(
            ts=jnp.where(o_exp, trigger_ts[o_trig_row], elem_ts[o_elem]),
            kind=jnp.where(o_exp, np.int8(KIND_EXPIRED), np.int8(KIND_CURRENT)),
            valid=o_valid,
            cols={n: elem_cols[n][o_elem] for n in elem_cols},
        )

        # --- membership matrix for exact min/max/distinct ---
        # position-based: element is "in the window" from its CURRENT output row
        # (ring elements: from the start) until its EXPIRED output row, which
        # reproduces the reference's one-by-one add/remove ordering exactly.
        inv = jnp.argsort(order)  # candidate index -> sorted output position
        birth_pos = jnp.where(
            own_row >= 0, inv[k + jnp.clip(own_row, 0, bsz - 1)], np.int32(-1)
        )
        death_pos = jnp.where(evict, inv[jnp.arange(k)], BIG)
        alive_src = present
        pos_row = jnp.arange(k + bsz)
        member = (
            alive_src[None, :]
            & (birth_pos[None, :] <= pos_row[:, None])
            & (pos_row[:, None] < death_pos[None, :])
        )
        member_cols = {
            (self.ref, None, n): elem_cols[n] for n in elem_cols
        }
        member_cols[(self.ref, None, TS_ATTR)] = elem_ts
        member_env = Env(member_cols, now=flow.now)

        new_state = self._ring_state(
            state, evict, valid_cur, rank, c, total, b, bwts, seq_batch
        )

        aux = dict(flow.aux)
        if self.needs_scheduler and self.t is not None:
            surv_wts = jnp.where(new_state["seq"] >= 0, new_state["wts"], NO_TIMER - self.t)
            aux["next_timer"] = surv_wts.min() + self.t

        return new_state, Flow(
            batch=out,
            ref=flow.ref,
            now=flow.now,
            extra_cols={},
            member=member,
            member_env=member_env,
            aux=aux,
            tables=flow.tables,
        )


    def _ring_state(
        self, state, evict, valid_cur, rank, c, total, b, bwts, seq_batch
    ):
        """Post-step ring buffers, shared by the sorted and length-only paths.
        Rows already evicted within this batch (expired before the batch
        ended) must NOT be re-inserted, or they would expire a second time."""
        w = self.w
        ring_evicted = evict[:w]
        batch_evicted = evict[w:]
        insert = valid_cur & ~batch_evicted & (rank >= c - w)
        slots = jnp.where(insert, (total + rank) % w, np.int64(w)).astype(jnp.int32)
        new_seq = jnp.where(ring_evicted, np.int64(-1), state["seq"])
        return {
            "cols": {
                n: _place_ring(state["cols"][n], ring_evicted, slots, b.cols[n])
                for n in b.cols
            },
            "ts": _place_ring(state["ts"], ring_evicted, slots, b.ts),
            "wts": _place_ring(state["wts"], ring_evicted, slots, bwts),
            "seq": _set_at(new_seq, slots, seq_batch),
            "total": total + c,
        }

    def _apply_length(
        self, state, flow, b, bsz, w, total, valid_cur, bwts, rank, c,
        seq_batch, elem_ts, elem_cols, present,
        trig_rank, len_trig_valid, perm,
    ):
        """Sort-free length-window step (see apply). Positions:
        insertion i (rank order) emits EXPIRED at i + E_i - 1 when it evicts
        (E = inclusive eviction count) and its CURRENT at i + E_i."""
        ranks = jnp.arange(bsz, dtype=jnp.int32)
        in_rank = ranks < c
        # insertion i evicts iff the window is full at that point
        e = in_rank & (total + ranks >= w)
        E = jnp.cumsum(e.astype(jnp.int32))
        cur_pos_rank = ranks + E
        exp_pos_rank = jnp.where(e, cur_pos_rank - 1, BIG)

        # evicted element (seq = total + i - w): a ring slot if it predates
        # this batch, else the batch row of rank i - w
        seq_ev = total + ranks.astype(jnp.int64) - w
        from_ring = seq_ev < total
        ring_slot = jnp.where(seq_ev >= 0, seq_ev % w, 0).astype(jnp.int32)
        batch_rank = jnp.clip(ranks - w, 0, bsz - 1)
        elem_idx = jnp.where(
            from_ring, ring_slot, w + perm[batch_rank]
        ).astype(jnp.int32)

        n_out = 2 * bsz
        trig_ts = b.ts[perm[jnp.clip(ranks, 0, bsz - 1)]]  # trigger row ts
        out_ts = jnp.zeros((n_out,), jnp.int64)
        out_kind = jnp.zeros((n_out,), jnp.int8)
        out_valid = jnp.zeros((n_out,), jnp.bool_)
        out_cols = {n: jnp.zeros((n_out,), a.dtype) for n, a in b.cols.items()}

        # scatter EXPIREDs (rank space); set_at keeps int64 lanes fast
        exp_dst = jnp.where(e, exp_pos_rank, n_out)
        out_ts = _set_at(out_ts, exp_dst, trig_ts)
        out_kind = out_kind.at[exp_dst].set(np.int8(KIND_EXPIRED), mode="drop")
        out_valid = out_valid.at[exp_dst].set(True, mode="drop")
        for n in out_cols:
            out_cols[n] = _set_at(out_cols[n], exp_dst, elem_cols[n][elem_idx])
        # scatter CURRENTs (row space: row r has rank[r], position via gather)
        cur_pos_row = cur_pos_rank[jnp.clip(rank, 0, bsz - 1)]
        cur_dst = jnp.where(valid_cur, cur_pos_row, n_out)
        out_ts = _set_at(out_ts, cur_dst, b.ts)
        out_valid = out_valid.at[cur_dst].set(True, mode="drop")
        for n in out_cols:
            out_cols[n] = _set_at(out_cols[n], cur_dst, b.cols[n])
        out = EventBatch(ts=out_ts, kind=out_kind, valid=out_valid, cols=out_cols)

        # --- membership matrix (same contract as the sorted path) ---
        own_row_rank = rank  # row -> rank
        birth_pos = jnp.concatenate(
            [
                jnp.full((w,), -1, jnp.int32),
                jnp.where(valid_cur, cur_pos_row, np.int32(-1)),
            ]
        )
        E_at = E[jnp.clip(trig_rank, 0, bsz - 1)]
        death_pos = jnp.where(
            len_trig_valid, trig_rank + E_at - 1, BIG
        )
        pos_row = jnp.arange(n_out)
        member = (
            present[None, :]
            & (birth_pos[None, :] <= pos_row[:, None])
            & (pos_row[:, None] < death_pos[None, :])
        )
        member_cols = {(self.ref, None, n): elem_cols[n] for n in elem_cols}
        member_cols[(self.ref, None, TS_ATTR)] = elem_ts
        member_env = Env(member_cols, now=flow.now)

        new_state = self._ring_state(
            state, len_trig_valid, valid_cur, rank, c, total, b, bwts, seq_batch
        )
        return new_state, Flow(
            batch=out,
            ref=flow.ref,
            now=flow.now,
            extra_cols={},
            member=member,
            member_env=member_env,
            aux=dict(flow.aux),
            tables=flow.tables,
        )

    @staticmethod
    def _view_perm(state):
        """THE ring-slot -> logical-insertion-order permutation, shared by
        view() and view_seq(): join lineage pairs view_seq's seq lane with
        view's cols/mask by position, so the two must never drift."""
        mask = state["seq"] >= 0
        perm = jnp.argsort(
            jnp.where(mask, state["seq"], jnp.iinfo(jnp.int64).max)
        ).astype(jnp.int32)
        return mask, perm

    def view(self, state):
        mask, perm = self._view_perm(state)
        cols = {n: c[perm] for n, c in state["cols"].items()}
        return cols, state["ts"][perm], mask[perm]

    def view_seq(self, state):
        _mask, perm = self._view_perm(state)
        return state["seq"][perm]


def _place_ring(old, evicted, slots, vals):
    # set_at: 64-bit lanes (ts/wts/seq/long cols) ride the int32-pair scatter
    # (a raw 64-bit scatter-set serializes on TPU, ops/scatter.py).
    # Zero typed to the lane dtype: a weak `0` literal promotes BOOL lanes
    # to int64, which breaks the fused scan carry (bool cols reach the
    # fused path since the bit-packed wire, core/wire.py)
    return _set_at(
        jnp.where(evicted, jnp.zeros((), old.dtype), old), slots, vals
    )


# ---------------------------------------------------------------------------
# batch (tumbling) family: lengthBatch / timeBatch / externalTimeBatch
# ---------------------------------------------------------------------------


class BatchWindow(WindowStage):
    """Tumbling buckets. Flush every `length` events (lengthBatch) or at each
    `duration` boundary of the window-time (timeBatch / externalTimeBatch).
    On flush the reference emits: prev-bucket EXPIREDs, RESET, then the closing
    bucket's CURRENTs (LengthBatchWindowProcessor.java:108-160); sort keys
    (trigger_row*4 + {0 expired, 1 reset, 2 current}) reproduce that order.

    State invariant: the open bucket holds < flush size (cur_n < n for
    lengthBatch); `prev` holds the last flushed bucket awaiting expiry.

    `emit_expired`: the query runtime clears this when nothing downstream can
    observe EXPIRED rows (output is `insert [current] into`, no rate limiter,
    no membership-consuming aggregator) — the expired candidate lanes are then
    omitted entirely, halving the flow every downstream selector op runs over.
    """

    is_batch = True
    emit_expired = True

    def __init__(
        self,
        schema: StreamSchema,
        ref: str,
        capacity: int,
        length: Optional[int] = None,
        duration_ms: Optional[int] = None,
        time_attr: Optional[str] = None,
        use_scheduler: bool = False,
        start_time: Optional[int] = None,
        timeout_ms: Optional[int] = None,
    ):
        if (length is None) == (duration_ms is None):
            raise SiddhiAppCreationError("batch window needs length xor duration")
        self.schema = schema
        self.ref = ref
        self.w = int(capacity)
        self.n = length
        self.t = duration_ms
        self.time_attr = time_attr
        # externalTimeBatch idle timeout: a WALL-CLOCK deadline re-armed on
        # every event; a TIMER arriving with a nonempty open bucket force-
        # closes it (reference: ExternalTimeBatchWindowProcessor timeout
        # scheduling, lines 243-258)
        self.timeout_ms = timeout_ms
        self.needs_scheduler = use_scheduler or timeout_ms is not None
        self.start_time = start_time

    def share_signature(self):
        if self.needs_scheduler:
            return None  # timer-armed: host scheduling owns per-query state
        # emit_expired is part of the identity: the query runtime clears it
        # per query, and a no-expired bucket may skip prev-bucket writes
        return (
            "BatchWindow", self.w, self.n, self.t, self.time_attr,
            self.start_time, self.emit_expired,
        )

    def init_state(self):
        w = self.w
        zero_cols = {
            n: jnp.zeros((w,), a.dtype)
            for n, a in self.schema.empty_batch(1).cols.items()
        }
        return {
            "cur_cols": zero_cols,
            "cur_ts": jnp.zeros((w,), jnp.int64),
            "cur_n": jnp.zeros((), jnp.int32),
            "prev_cols": {n: jnp.zeros_like(a) for n, a in zero_cols.items()},
            "prev_ts": jnp.zeros((w,), jnp.int64),
            "prev_n": jnp.zeros((), jnp.int32),
            # open-bucket start time (timeBatch family); -1 = no bucket yet
            "bucket_start": jnp.full((), -1, jnp.int64),
            # externalTimeBatch idle timeout: the latest armed WALL-CLOCK
            # deadline; a TIMER flushes only when it has genuinely elapsed
            # (the scheduler cannot extend a pending deadline, so stale
            # early timers must be ignored here)
            "timeout_deadline": jnp.full((), NO_TIMER, jnp.int64),
        }

    def apply(self, state, flow: Flow):
        b = flow.batch
        bsz = b.capacity
        w = self.w
        rows = jnp.arange(bsz, dtype=jnp.int32)
        valid_cur = b.valid & (b.kind == KIND_CURRENT)
        is_timer = b.valid & (b.kind == KIND_TIMER)
        bwts = (
            b.cols[self.time_attr].astype(jnp.int64)
            if self.time_attr is not None
            else b.ts
        )
        rank = jnp.cumsum(valid_cur.astype(jnp.int32)) - valid_cur.astype(jnp.int32)
        c = valid_cur.sum(dtype=jnp.int32)
        perm = jnp.argsort(~valid_cur, stable=True).astype(jnp.int32)  # rank -> row
        cur_n0 = state["cur_n"]

        new_bucket_start = state["bucket_start"]
        if self.n is not None:
            # --- lengthBatch: flush f triggers at the row completing (f+1)*n ---
            # at most bsz//n + 1 flushes can occur per batch (carried bucket
            # holds < n), so the flush bookkeeping lanes are [F], not [bsz] —
            # every downstream candidate lane and the selector's whole flow
            # shrink with them
            n = self.n
            F = min(bsz // n + 2, bsz)
            pos = cur_n0 + rank  # fill position of each current row
            e_row = pos // n  # flush index at which the row's bucket closes
            n_flush = (cur_n0 + c) // n
            f_arr = jnp.arange(F, dtype=jnp.int32)
            trig_rank_f = (f_arr + 1) * n - 1 - cur_n0
            flush_exists = (trig_rank_f >= 0) & (trig_rank_f < c)
            row_of_flush = jnp.where(
                flush_exists, perm[jnp.clip(trig_rank_f, 0, bsz - 1)], bsz - 1
            )
        else:
            # --- timeBatch: flush when a trigger row enters a later bucket ---
            trigger_ok = valid_cur | is_timer
            if self.start_time is not None:
                start0 = np.int64(self.start_time)
            else:
                first_trig = jnp.argmax(trigger_ok)
                start0 = jnp.where(
                    state["bucket_start"] >= 0,
                    state["bucket_start"],
                    jnp.where(trigger_ok.any(), bwts[first_trig], np.int64(-1)),
                )
            F = bsz  # time-driven flush count is bounded only by trigger rows
            rel = jnp.maximum(bwts - start0, 0)
            g = jnp.where(trigger_ok & (start0 >= 0), rel // self.t, np.int64(0))
            # the open bucket's index carries ACROSS batches: with an
            # explicit start time, start0 is a constant, so the first row of
            # every batch would otherwise compare against bucket 0 and flush
            # spuriously (for first-event starts, bucket_start == start0 and
            # the carried index is 0 — unchanged)
            carried_g = jnp.where(
                state["bucket_start"] >= 0,
                jnp.maximum(state["bucket_start"] - start0, 0) // self.t,
                np.int64(0),
            )
            open_g = _cummax(jnp.maximum(g, carried_g))
            prev_open = jnp.concatenate([carried_g[None], open_g[:-1]])
            had_bucket = (state["bucket_start"] >= 0) | (
                jnp.cumsum(trigger_ok.astype(jnp.int32)) - trigger_ok.astype(jnp.int32) > 0
            )
            flush_here = trigger_ok & (g > prev_open) & had_bucket
            if self.timeout_ms is not None:
                # an ELAPSED idle-timeout TIMER force-closes a nonempty open
                # bucket WITHOUT advancing the bucket grid: later events whose
                # external time falls in the same grid bucket open a fresh
                # bucket there (reference: ExternalTimeBatchWindowProcessor
                # clears currentEventChunk but keeps endTime). Positional: a
                # CURRENT row earlier in this batch re-arms the deadline to
                # now + timeout (which cannot have elapsed at this same now),
                # so only a TIMER with no prior CURRENT row (`rank == 0`) can
                # see a genuinely stale deadline — a stale timer after a
                # same-batch refill must not force-close the bucket
                timeout_flush = (
                    is_timer
                    & (rank == 0)
                    & (cur_n0 > 0)
                    & (jnp.asarray(flow.now, jnp.int64)
                       >= state["timeout_deadline"])
                )
                flush_here = flush_here | timeout_flush
            e_row = jnp.cumsum(flush_here.astype(jnp.int32))  # inclusive: flush at i precedes row i
            n_flush = flush_here.sum(dtype=jnp.int32)
            row_of_flush = jnp.where(
                rows < n_flush,
                jnp.argsort(jnp.where(flush_here, rows, BIG)).astype(jnp.int32),
                bsz - 1,
            )
            flush_exists = rows < n_flush
            new_bucket_start = jnp.where(
                trigger_ok.any() & (start0 >= 0), start0 + open_g[-1] * self.t, start0
            )
            e_row = jnp.where(valid_cur, e_row, 0)

        any_flush = n_flush > 0

        def flush_key(f, kindbit):
            return row_of_flush[jnp.clip(f, 0, F - 1)] * 4 + kindbit

        # --- candidates ---
        # carried open bucket: CURRENT at flush 0, EXPIRED at flush 1
        cw = jnp.arange(w, dtype=jnp.int32)
        carried_valid = cw < cur_n0
        cc_cur_key = jnp.where(carried_valid & any_flush, flush_key(0, 2), BIG)
        cc_exp_key = jnp.where(carried_valid & (n_flush > 1), flush_key(1, 0), BIG)
        # prev bucket: EXPIRED at flush 0
        prev_valid = cw < state["prev_n"]
        pv_exp_key = jnp.where(prev_valid & any_flush, flush_key(0, 0), BIG)
        # batch rows: CURRENT at their closing flush, EXPIRED one flush later
        row_emit = valid_cur & (e_row < n_flush)
        bt_cur_key = jnp.where(row_emit, flush_key(e_row.astype(jnp.int32), 2), BIG)
        bt_exp_key = jnp.where(
            row_emit & (e_row + 1 < n_flush), flush_key(e_row.astype(jnp.int32) + 1, 0), BIG
        )
        # resets: one per flush ([F] lanes)
        rs_key = jnp.where(flush_exists, row_of_flush * 4 + 1, BIG)

        # element table: [0,w) carried-cur, [w,2w) prev, [2w,2w+bsz) batch
        # (used by the membership env only; the candidate VALUE lanes below
        # are built by concatenating the same slices, so the big sort carries
        # them as payloads instead of per-lane [order] gathers — gathers
        # serialize on the TPU scalar core, sort payloads ride the VPU)
        elem_cols = {
            nm: jnp.concatenate([state["cur_cols"][nm], state["prev_cols"][nm], b.cols[nm]])
            for nm in b.cols
        }
        elem_ts = jnp.concatenate([state["cur_ts"], state["prev_ts"], b.ts])

        if self.emit_expired:
            cand_key = jnp.concatenate([cc_cur_key, cc_exp_key, pv_exp_key, bt_cur_key, bt_exp_key, rs_key])
            lanes = lambda cur, prev, bat: jnp.concatenate(  # noqa: E731
                [cur, cur, prev, bat, bat, jnp.broadcast_to(cur[0], (F,))]
            )
            cand_kind = jnp.concatenate(
                [
                    jnp.full((w,), KIND_CURRENT, jnp.int8),
                    jnp.full((w,), KIND_EXPIRED, jnp.int8),
                    jnp.full((w,), KIND_EXPIRED, jnp.int8),
                    jnp.full((bsz,), KIND_CURRENT, jnp.int8),
                    jnp.full((bsz,), KIND_EXPIRED, jnp.int8),
                    jnp.full((F,), KIND_RESET, jnp.int8),
                ]
            )
            tie = jnp.concatenate([cw, cw, cw, rows + w, rows + w, jnp.arange(F, dtype=jnp.int32)])
            bt_cur_off = 3 * w
        else:
            # CURRENT-only consumers: drop the three expired lanes
            cand_key = jnp.concatenate([cc_cur_key, bt_cur_key, rs_key])
            lanes = lambda cur, prev, bat: jnp.concatenate(  # noqa: E731
                [cur, bat, jnp.broadcast_to(cur[0], (F,))]
            )
            cand_kind = jnp.concatenate(
                [
                    jnp.full((w,), KIND_CURRENT, jnp.int8),
                    jnp.full((bsz,), KIND_CURRENT, jnp.int8),
                    jnp.full((F,), KIND_RESET, jnp.int8),
                ]
            )
            tie = jnp.concatenate([cw, rows + w, jnp.arange(F, dtype=jnp.int32)])
            bt_cur_off = w
        cand_valid = cand_key < BIG
        # ONE payload sort orders the candidates AND carries kind/valid/ts and
        # every attribute value lane
        ncand_i = cand_key.shape[0]
        cidx = jnp.arange(ncand_i, dtype=jnp.int32)
        col_names = list(b.cols)
        sorted_ops = jax.lax.sort(
            (
                jnp.where(cand_valid, cand_key, BIG), tie, cidx,
                cand_kind, cand_valid, cand_key,
                lanes(state["cur_ts"], state["prev_ts"], b.ts),
                *(
                    lanes(state["cur_cols"][nm], state["prev_cols"][nm], b.cols[nm])
                    for nm in col_names
                ),
            ),
            num_keys=2, is_stable=False,
        )
        (_, _, order, o_kind, o_valid, o_key_raw, o_ts) = sorted_ops[:7]
        o_cols = dict(zip(col_names, sorted_ops[7:]))
        o_key = jnp.where(o_valid, o_key_raw, BIG)
        if self.emit_expired:
            # EXPIRED rows carry their flush trigger's timestamp
            trig_ts = b.ts[jnp.clip(o_key // 4, 0, bsz - 1)]
            out_ts = jnp.where(o_kind == KIND_EXPIRED, trig_ts, o_ts)
        else:
            out_ts = o_ts
        out = EventBatch(
            ts=out_ts,
            kind=o_kind,
            valid=o_valid,
            cols=o_cols,
        )

        # --- membership (bucket contents; position-based, see SlidingWindow) ---
        # An element is a member from its CURRENT output row (which follows its
        # flush's RESET) until its own EXPIRED row at the NEXT flush — the
        # reference's one-by-one add/remove ordering: reset clears, the
        # bucket's currents accumulate, the next flush's expireds remove.
        # Prev-bucket elements are never members (their bucket's reset already
        # cleared the deque; their EXPIRED events remove from empty — a no-op).
        # candidate index -> sorted output position, via a payload sort; the
        # per-lane reads below are SLICES of inv (cw/rows are aranges), not
        # gathers
        (inv,) = _permute_by(order, cidx)
        ncand = ncand_i
        birth_cc = jnp.where(carried_valid & any_flush, inv[:w], BIG)
        birth_bt = jnp.where(
            row_emit, inv[bt_cur_off : bt_cur_off + bsz], BIG
        )
        # without expired lanes there are no death positions, so membership
        # cannot be expressed — hand downstream None and any (future) member
        # consumer degrades to its memberless path (`member is None` guards)
        if self.emit_expired:
            death_cc = jnp.where(
                carried_valid & (n_flush > 1), inv[w : 2 * w], BIG
            )
            death_bt = jnp.where(
                row_emit & (e_row + 1 < n_flush),
                inv[3 * w + bsz : 3 * w + 2 * bsz],
                BIG,
            )
            e_birth = jnp.concatenate([birth_cc, jnp.full((w,), BIG, jnp.int32), birth_bt])
            e_death = jnp.concatenate([death_cc, jnp.full((w,), -1, jnp.int32), death_bt])
            e_alive = jnp.concatenate([carried_valid & any_flush, jnp.zeros((w,), bool), row_emit])
            pos_row = jnp.arange(ncand)
            member = (
                e_alive[None, :]
                & (e_birth[None, :] <= pos_row[:, None])
                & (pos_row[:, None] < e_death[None, :])
            )
            member_cols = {(self.ref, None, nm): elem_cols[nm] for nm in elem_cols}
            member_cols[(self.ref, None, TS_ATTR)] = elem_ts
            member_env = Env(member_cols, now=flow.now)
        else:
            member = None
            member_env = None

        # --- new buffers ---
        # open bucket: elements whose bucket index == n_flush (not yet closed)
        remaining = valid_cur & (e_row == n_flush)
        keep_carried = ~any_flush  # carried stays only if nothing flushed
        if self.n is not None:
            rem_slot = jnp.where(remaining, pos - n_flush * self.n, w)
        else:
            rem_rank = jnp.cumsum(remaining.astype(jnp.int32)) - remaining.astype(jnp.int32)
            rem_slot = jnp.where(
                remaining, rem_rank + jnp.where(keep_carried, cur_n0, 0), w
            )
        rem_slot = rem_slot.astype(jnp.int32)

        def place_cur(old, vals):
            kept = jnp.where(keep_carried, old, jnp.zeros_like(old))
            return _compact_set_at(kept, rem_slot, vals)

        new_cur_n = jnp.where(keep_carried, cur_n0, 0) + remaining.sum(dtype=jnp.int32)

        # prev bucket: last flushed bucket (carried if it closed last, + rows)
        in_last = row_emit & (e_row == n_flush - 1)
        carried_in_last = carried_valid & (n_flush == 1)
        n_carried_last = jnp.where(n_flush == 1, cur_n0, 0)
        lb_rank = jnp.cumsum(in_last.astype(jnp.int32)) - in_last.astype(jnp.int32)
        lb_slot_c = jnp.where(carried_in_last, cw, w).astype(jnp.int32)
        lb_slot_b = jnp.where(in_last, n_carried_last + lb_rank, w).astype(jnp.int32)

        def place_prev(old_prev, carried_vals, batch_vals):
            base = jnp.where(any_flush, jnp.zeros_like(old_prev), old_prev)
            base = _set_at(base, lb_slot_c, carried_vals)
            return _compact_set_at(base, lb_slot_b, batch_vals)

        new_prev_n = jnp.where(
            any_flush, n_carried_last + in_last.sum(dtype=jnp.int32), state["prev_n"]
        )

        new_state = {
            "cur_cols": {nm: place_cur(state["cur_cols"][nm], b.cols[nm]) for nm in b.cols},
            "cur_ts": place_cur(state["cur_ts"], b.ts),
            "cur_n": new_cur_n,
            "prev_cols": {
                nm: place_prev(state["prev_cols"][nm], state["cur_cols"][nm], b.cols[nm])
                for nm in b.cols
            },
            "prev_ts": place_prev(state["prev_ts"], state["cur_ts"], b.ts),
            "prev_n": new_prev_n,
            "bucket_start": new_bucket_start,
            "timeout_deadline": state["timeout_deadline"],
        }

        aux = dict(flow.aux)
        if self.timeout_ms is not None:
            # wall-clock idle deadline: every arriving CURRENT event pushes
            # it forward; with an empty open bucket there is none. A stale
            # timer (armed before the push) re-arms the true deadline via
            # next_timer below.
            now64 = jnp.asarray(flow.now, jnp.int64)
            new_state["timeout_deadline"] = jnp.where(
                valid_cur.any(),
                now64 + self.timeout_ms,
                jnp.where(
                    new_state["cur_n"] > 0,
                    state["timeout_deadline"],
                    np.int64(NO_TIMER),
                ),
            )
            aux["next_timer"] = jnp.where(
                new_state["cur_n"] > 0,
                new_state["timeout_deadline"],
                np.int64(NO_TIMER),
            )
        elif self.needs_scheduler and self.t is not None:
            aux["next_timer"] = jnp.where(
                new_state["bucket_start"] >= 0,
                new_state["bucket_start"] + self.t,
                np.int64(NO_TIMER),
            )

        return new_state, Flow(
            batch=out,
            ref=flow.ref,
            now=flow.now,
            extra_cols={},
            member=member,
            member_env=member_env,
            aux=aux,
            tables=flow.tables,
        )


    def view(self, state):
        # the open (current) bucket is the probe-able window content
        # (reference: LengthBatchWindowProcessor.find over currentEventQueue)
        mask = jnp.arange(self.w, dtype=jnp.int32) < state["cur_n"]
        return state["cur_cols"], state["cur_ts"], mask


# ---------------------------------------------------------------------------
# factory
# ---------------------------------------------------------------------------


def make_window(
    spec: WindowSpec,
    schema: StreamSchema,
    ref: str,
    scope: Scope,
    time_capacity: int = DEFAULT_TIME_CAPACITY,
) -> WindowStage:
    """Reference: SingleInputStreamParser.generateProcessor window dispatch."""
    name = spec.name.lower() if spec.namespace is None else f"{spec.namespace}:{spec.name}"
    if name == "length":
        n = _const_param(spec, 0, "length")
        return SlidingWindow(schema, ref, capacity=n)
    if name == "time":
        t = _const_param(spec, 0, "duration")
        return SlidingWindow(
            schema, ref, capacity=time_capacity, duration_ms=t, use_scheduler=True
        )
    if name == "timelength":
        t = _const_param(spec, 0, "duration")
        n = _const_param(spec, 1, "length")
        return SlidingWindow(
            schema, ref, capacity=n, duration_ms=t, use_scheduler=True
        )
    if name == "externaltime":
        attr = _time_attr(spec, 0, schema)
        scope.record_key((ref, None, attr))
        t = _const_param(spec, 1, "duration")
        return SlidingWindow(
            schema, ref, capacity=time_capacity, duration_ms=t, time_attr=attr
        )
    if name == "lengthbatch":
        n = _const_param(spec, 0, "length")
        return BatchWindow(schema, ref, capacity=n, length=n)
    if name == "timebatch":
        t = _const_param(spec, 0, "duration")
        start = _const_param(spec, 1, "start time") if len(spec.parameters) > 1 else None
        return BatchWindow(
            schema, ref, capacity=time_capacity, duration_ms=t,
            use_scheduler=True, start_time=start,
        )
    if name == "externaltimebatch":
        attr = _time_attr(spec, 0, schema)
        scope.record_key((ref, None, attr))
        t = _const_param(spec, 1, "duration")
        start = _const_param(spec, 2, "start time") if len(spec.parameters) > 2 else None
        timeout = (
            _const_param(spec, 3, "timeout")
            if len(spec.parameters) > 3 else None
        )
        return BatchWindow(
            schema, ref, capacity=time_capacity, duration_ms=t, time_attr=attr,
            start_time=start, timeout_ms=timeout,
        )
    if name == "sort":
        from siddhi_tpu.core.windows_special import SortWindow
        from siddhi_tpu.query_api.expression import Constant, Variable

        n = _const_param(spec, 0, "length")
        keys: list[tuple[str, bool]] = []
        i = 1
        params = spec.parameters
        while i < len(params):
            p = params[i]
            if not isinstance(p, Variable):
                raise SiddhiAppCreationError(
                    "sort window parameters after the length must be "
                    "attribute [, 'asc'|'desc'] pairs"
                )
            desc = False
            if i + 1 < len(params) and isinstance(params[i + 1], Constant) and str(
                params[i + 1].value
            ).lower() in ("asc", "desc"):
                desc = str(params[i + 1].value).lower() == "desc"
                i += 1
            keys.append((p.attribute, desc))
            i += 1
        for a, _d in keys:
            scope.record_key((ref, None, a))
        return SortWindow(schema, ref, n, keys)
    if name == "frequent":
        from siddhi_tpu.core.windows_special import FrequentWindow
        from siddhi_tpu.query_api.expression import Variable

        n = _const_param(spec, 0, "count")
        attrs = []
        for p in spec.parameters[1:]:
            if not isinstance(p, Variable):
                raise SiddhiAppCreationError("frequent window keys must be attributes")
            attrs.append(p.attribute)
        for a in (attrs or schema.attr_names):  # no keys = whole-event key
            scope.record_key((ref, None, a))
        return FrequentWindow(schema, ref, n, attrs)
    if name == "lossyfrequent":
        from siddhi_tpu.core.windows_special import LossyFrequentWindow
        from siddhi_tpu.query_api.expression import Variable

        support = _const_raw(spec, 0, "support threshold")
        if len(spec.parameters) > 1 and not isinstance(spec.parameters[1], Variable):
            error = _const_raw(spec, 1, "error bound")
            rest = spec.parameters[2:]
        else:
            error = float(support) / 10.0  # reference default error bound
            rest = spec.parameters[1:]
        attrs = []
        for p in rest:
            if not isinstance(p, Variable):
                raise SiddhiAppCreationError(
                    "lossyFrequent window keys must be attributes"
                )
            attrs.append(p.attribute)
        for a in (attrs or schema.attr_names):  # no keys = whole-event key
            scope.record_key((ref, None, a))
        return LossyFrequentWindow(schema, ref, float(support), float(error), attrs)
    if name == "cron":
        from siddhi_tpu.core.windows_special import CronWindow

        expr = _const_raw(spec, 0, "cron expression")
        return CronWindow(schema, ref, str(expr), capacity=time_capacity)
    raise SiddhiAppCreationError(f"unknown window type '{spec.name}'")


def _time_attr(spec: WindowSpec, i: int, schema: StreamSchema) -> str:
    from siddhi_tpu.query_api.expression import Variable

    p = spec.parameters[i]
    if not isinstance(p, Variable):
        raise SiddhiAppCreationError(f"window {spec.name}: parameter {i} must be an attribute")
    if schema.type_of(p.attribute) not in (AttrType.LONG, AttrType.INT):
        raise SiddhiAppCreationError("external time attribute must be long")
    return p.attribute
