"""Statistics: throughput/latency/buffer trackers + periodic reporters.

Reference: util/statistics/metrics/SiddhiStatisticsManager.java:35-80
(Dropwizard MetricRegistry + console/JMX reporters), ThroughputTracker.java,
LatencyTracker.java, BufferedEventsTracker.java; enabled by
`@app:statistics(reporter='console', interval='N')` (SiddhiAppParser.java:106-142)
and toggled at runtime (SiddhiAppRuntime.enableStats :682). Metric naming
follows util/SiddhiConstants.java METRIC_* conventions.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class ThroughputTracker:
    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self.count += n


class LatencyTracker:
    """markIn/markOut around a processing chain (per-thread nesting safe)."""

    def __init__(self, name: str):
        self.name = name
        self.total_ns = 0
        self.samples = 0
        self._tls = threading.local()
        self._lock = threading.Lock()

    def mark_in(self) -> None:
        self._tls.t0 = time.perf_counter_ns()

    def mark_out(self) -> None:
        t0 = getattr(self._tls, "t0", None)
        if t0 is None:
            return
        dt = time.perf_counter_ns() - t0
        with self._lock:
            self.total_ns += dt
            self.samples += 1

    @property
    def avg_ms(self) -> float:
        return (self.total_ns / self.samples) / 1e6 if self.samples else 0.0


class BufferedEventsTracker:
    """Occupancy of async ingress rings (reference: BufferedEventsTracker on
    Disruptor rings, StreamJunction.java:334-345)."""

    def __init__(self, name: str):
        self.name = name
        self.get_size = lambda: 0

    def register(self, fn) -> None:
        self.get_size = fn


class StatisticsManager:
    """reference: SiddhiStatisticsManager — registry + reporter thread."""

    def __init__(self, app_name: str, reporter: str = "console", interval_s: float = 60.0):
        self.app_name = app_name
        self.reporter = reporter
        self.interval_s = float(interval_s)
        self.throughput: dict[str, ThroughputTracker] = {}
        self.latency: dict[str, LatencyTracker] = {}
        self.buffered: dict[str, BufferedEventsTracker] = {}
        # failed dispatches / sink publishes per component (reference analog:
        # the error counters Siddhi's metrics registry keeps per junction)
        self.errors: dict[str, ThroughputTracker] = {}
        # name -> () -> bytes; the TPU-native analog of the reference's
        # ObjectSizeCalculator memory metric (util/statistics/memory/):
        # device-buffer bytes held by each component's carried state
        self.memory: dict[str, callable] = {}
        self.enabled = True
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def throughput_tracker(self, name: str) -> ThroughputTracker:
        return self.throughput.setdefault(name, ThroughputTracker(name))

    def latency_tracker(self, name: str) -> LatencyTracker:
        return self.latency.setdefault(name, LatencyTracker(name))

    def buffered_tracker(self, name: str) -> BufferedEventsTracker:
        return self.buffered.setdefault(name, BufferedEventsTracker(name))

    def error_tracker(self, name: str) -> ThroughputTracker:
        return self.errors.setdefault(name, ThroughputTracker(name))

    def register_memory(self, name: str, fn) -> None:
        """fn() -> device bytes held by the named component's state."""
        self.memory[name] = fn

    # ---- reporting ---------------------------------------------------------

    def report(self) -> dict:
        mem = {}
        for n, fn in self.memory.items():
            try:
                mem[n] = int(fn())
            except Exception:
                mem[n] = -1
        return {
            "app": self.app_name,
            "throughput": {n: t.count for n, t in self.throughput.items()},
            "latency_avg_ms": {
                n: round(t.avg_ms, 3) for n, t in self.latency.items()
            },
            "buffered": {n: t.get_size() for n, t in self.buffered.items()},
            "errors": {n: t.count for n, t in self.errors.items()},
            "memory_bytes": mem,
        }

    def start_reporting(self) -> None:
        if self._thread is not None or self.reporter not in ("console", "log"):
            return
        self._stop.clear()

        def run():
            import logging

            log = logging.getLogger(f"siddhi_tpu.statistics.{self.app_name}")
            while not self._stop.wait(self.interval_s):
                if self.enabled:
                    rep = self.report()
                    if self.reporter == "console":
                        print(f"[siddhi_tpu stats] {rep}", flush=True)
                    else:
                        log.info("%s", rep)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def stop_reporting(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None
