"""Statistics — back-compat shim over `siddhi_tpu.observability`.

The statistics subsystem grew into a package: histogram metrics
(log-bucketed p50/p95/p99/p999 + EWMA rates), a reporter SPI
(console/log/JSON-lines/Prometheus via `manager.serve_metrics(port)`),
sampled event tracing, and device-budget profiling hooks. Everything that
used to live here keeps its import path and API:

  ThroughputTracker      count + 1m/5m EWMA rates
  LatencyTracker         mark_in/mark_out -> log-bucketed histogram
                         (nesting-safe via a per-thread mark stack)
  BufferedEventsTracker  async ring occupancy
  StatisticsManager      registry + reporter thread (+ device metrics,
                         per-subscriber error attribution)

Reference: util/statistics/metrics/SiddhiStatisticsManager.java:35-80
(Dropwizard MetricRegistry + console/JMX reporters); enabled by
`@app:statistics(reporter='console', interval='N')`
(SiddhiAppParser.java:106-142) and toggled at runtime
(SiddhiAppRuntime.enableStats :682).
"""

from __future__ import annotations

from siddhi_tpu.observability.metrics import (  # noqa: F401
    BufferedEventsTracker,
    LatencyTracker,
    LogHistogram,
    ThroughputTracker,
)
from siddhi_tpu.observability.registry import (  # noqa: F401
    JunctionDeviceStats,
    StatisticsManager,
)

__all__ = [
    "ThroughputTracker",
    "LatencyTracker",
    "LogHistogram",
    "BufferedEventsTracker",
    "StatisticsManager",
    "JunctionDeviceStats",
]
