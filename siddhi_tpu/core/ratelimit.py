"""Output rate limiters: `output [all|first|last] every N events / N sec` and
`output snapshot every N sec`.

Reference: query/output/ratelimit/OutputRateLimiter.java:38 and its 17
subclasses (event/*, time/*, snapshot/*). FIRST/LAST with a grouped query
automatically become per-group variants (reference: OutputParser
constructOutputRateLimiter dispatch). Rate limiting runs host-side over the
decoded output rows — rate-limited outputs are low-volume by construction, and
the buffered/held rows are exactly the host-visible product.

Rows are `(ts, kind, data, key)` tuples; `key` is the group-by key id (None
when the query has no group-by). Snapshot limiting holds the latest aggregate
row (per key when grouped) and re-emits it every interval with the snapshot
timestamp (reference: WrappedSnapshotOutputRateLimiter for aggregating
selectors; windowed full-content snapshots are approximated the same way).
"""

from __future__ import annotations

from typing import Optional

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.query_api.execution import (
    EventOutputRate,
    OutputRateType,
    SnapshotOutputRate,
    TimeOutputRate,
)

Row = tuple  # (ts, kind, data, key)


class RateLimiter:
    """Base: process() on each output chunk, on_timer() at period boundaries."""

    period_ms: Optional[int] = None  # not None => needs the scheduler

    def process(self, rows: list[Row], now: int) -> list[Row]:
        raise NotImplementedError

    def on_timer(self, t_ms: int) -> list[Row]:
        return []


class EventAllLimiter(RateLimiter):
    """Release buffered output in chunks of N events
    (reference: event/AllPerEventOutputRateLimiter)."""

    def __init__(self, n: int):
        self.n = n
        self.buf: list[Row] = []

    def process(self, rows, now):
        self.buf.extend(rows)
        out: list[Row] = []
        while len(self.buf) >= self.n:
            out.extend(self.buf[: self.n])
            del self.buf[: self.n]
        return out


class EventFirstLimiter(RateLimiter):
    """Emit the first event of every N (reference:
    event/FirstPerEventOutputRateLimiter); per-group: first per key within
    each N-chunk (FirstGroupByPerEventOutputRateLimiter)."""

    def __init__(self, n: int, grouped: bool):
        self.n = n
        self.grouped = grouped
        self.count = 0
        self.seen: set = set()
        self.held: list = []  # grouped: firsts buffered until chunk close

    def process(self, rows, now):
        out = []
        for r in rows:
            if self.grouped:
                # the grouped form BUFFERS each group's first and releases
                # the batch when the chunk closes (reference:
                # FirstGroupByPerEventOutputRateLimiter.process collects into
                # allComplexEventChunk and sends at counter == value)
                if r[3] not in self.seen:
                    self.seen.add(r[3])
                    self.held.append(r)
            elif self.count == 0:
                out.append(r)
            self.count += 1
            if self.count == self.n:
                self.count = 0
                self.seen.clear()
                out.extend(self.held)
                self.held.clear()
        return out


class EventLastLimiter(RateLimiter):
    """Emit the last event of every N (reference:
    event/LastPerEventOutputRateLimiter); per-group: last per key within each
    N-chunk (LastGroupByPerEventOutputRateLimiter)."""

    def __init__(self, n: int, grouped: bool):
        self.n = n
        self.grouped = grouped
        self.count = 0
        self.held: dict = {}  # key -> row (insertion ordered)

    def process(self, rows, now):
        out = []
        for r in rows:
            self.held[r[3] if self.grouped else None] = r
            self.count += 1
            if self.count == self.n:
                out.extend(self.held.values())
                self.held.clear()
                self.count = 0
        return out


class TimeAllLimiter(RateLimiter):
    """Flush everything each period (reference: time/AllPerTimeOutputRateLimiter)."""

    def __init__(self, t_ms: int):
        self.period_ms = t_ms
        self.buf: list[Row] = []

    def process(self, rows, now):
        self.buf.extend(rows)
        return []

    def on_timer(self, t_ms):
        out, self.buf = self.buf, []
        return out


class TimeFirstLimiter(RateLimiter):
    """First event per period emits immediately (reference:
    time/FirstPerTimeOutputRateLimiter; grouped: FirstGroupByPerTime...)."""

    def __init__(self, t_ms: int, grouped: bool):
        self.period_ms = t_ms
        self.grouped = grouped
        self.seen: set = set()
        self.emitted = False

    def process(self, rows, now):
        out = []
        for r in rows:
            if self.grouped:
                if r[3] not in self.seen:
                    self.seen.add(r[3])
                    out.append(r)
            elif not self.emitted:
                self.emitted = True
                out.append(r)
        return out

    def on_timer(self, t_ms):
        self.seen.clear()
        self.emitted = False
        return []


class TimeLastLimiter(RateLimiter):
    """Hold the last event (per key when grouped); emit at each period
    (reference: time/LastPerTimeOutputRateLimiter / LastGroupByPerTime...)."""

    def __init__(self, t_ms: int, grouped: bool):
        self.period_ms = t_ms
        self.grouped = grouped
        self.held: dict = {}

    def process(self, rows, now):
        for r in rows:
            self.held[r[3] if self.grouped else None] = r
        return []

    def on_timer(self, t_ms):
        out = list(self.held.values())
        self.held.clear()
        return out


class SnapshotLimiter(RateLimiter):
    """Re-emit the latest row (per key when grouped) every period with the
    snapshot timestamp (reference: snapshot/*PerSnapshotOutputRateLimiter)."""

    def __init__(self, t_ms: int, grouped: bool):
        self.period_ms = t_ms
        self.grouped = grouped
        self.held: dict = {}

    def process(self, rows, now):
        from siddhi_tpu.core.event import KIND_CURRENT

        for r in rows:
            if r[1] == KIND_CURRENT:  # snapshots track CURRENT state only
                self.held[r[3] if self.grouped else None] = r
        return []

    def on_timer(self, t_ms):
        return [(t_ms, kind, data, key) for (_ts, kind, data, key) in self.held.values()]


def build_rate_limiter(output_rate, grouped: bool) -> Optional[RateLimiter]:
    """reference: OutputParser.constructOutputRateLimiter dispatch table."""
    if output_rate is None:
        return None
    if isinstance(output_rate, EventOutputRate):
        if output_rate.events <= 0:
            raise SiddhiAppCreationError("output rate event count must be positive")
        if output_rate.type is OutputRateType.ALL:
            return EventAllLimiter(output_rate.events)
        if output_rate.type is OutputRateType.FIRST:
            return EventFirstLimiter(output_rate.events, grouped)
        return EventLastLimiter(output_rate.events, grouped)
    if isinstance(output_rate, TimeOutputRate):
        if output_rate.millis <= 0:
            raise SiddhiAppCreationError("output rate period must be positive")
        if output_rate.type is OutputRateType.ALL:
            return TimeAllLimiter(output_rate.millis)
        if output_rate.type is OutputRateType.FIRST:
            return TimeFirstLimiter(output_rate.millis, grouped)
        return TimeLastLimiter(output_rate.millis, grouped)
    if isinstance(output_rate, SnapshotOutputRate):
        return SnapshotLimiter(output_rate.millis, grouped)
    raise SiddhiAppCreationError(f"unknown output rate {output_rate!r}")
