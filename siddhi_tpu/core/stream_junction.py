"""Stream junctions and input handlers — host-side event routing.

Reference: stream/StreamJunction.java:58-404 (per-stream pub/sub fan-out) and
stream/input/InputManager.java / InputHandler.java. The device does all per-event
math; the junction packs host events into fixed-capacity columnar micro-batches
and fans them out to subscriber step functions. Synchronous dispatch mirrors the
reference's default pass-through mode; @async batching rides the same path via
send_batch.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Sequence

import numpy as np

from siddhi_tpu.core.event import EventBatch, StreamSchema
from siddhi_tpu.core.types import InternTable
from siddhi_tpu.testing import faults as _faults

# subscriber: fn(batch: EventBatch, now_ms: int) -> None
Subscriber = Callable[[EventBatch, int], None]


class StreamJunction:
    def __init__(
        self,
        schema: StreamSchema,
        interner: InternTable,
        batch_size: int = 64,
    ):
        self.schema = schema
        self.interner = interner
        self.batch_size = batch_size
        self.subscribers: list[Subscriber] = []
        self.subscriber_names: list[str] = []
        self.stream_callbacks: list[Callable] = []
        self.stream_callback_names: list[str] = []
        # fused-ingest wiring (core/ingest.py): subscribers that also register
        # a FuseEndpoint here can be run K-batches-per-dispatch by send_columns
        self.fuse_candidates: list = []
        self.fused_ingest = None
        # RLock: a query may legally insert into its own input stream
        # (reference allows self-feeding junctions); recursion stays on-thread
        self.lock = threading.RLock()
        # the owning app's process RLock (set by app_runtime._junction):
        # held across the whole per-batch fan-out so the snapshot barrier
        # (SnapshotService.full_snapshot) can never observe a torn
        # cross-query state mid-batch; None for junctions outside an app
        self.process_lock = None
        self.on_publish_stats: Callable[[int], None] | None = None
        self.on_error_stats: Callable[[int], None] | None = None
        # per-subscriber error attribution: factory(subscriber_name) -> add fn
        # for the `stream.<id>.subscriber.<name>` counter; adders cached here
        self.error_stats_factory: Callable[[str], Callable[[int], None]] | None = None
        self._sub_error_stats: dict[str, Callable[[int], None]] = {}
        # sampled event tracing (observability.tracing.Tracer); spans are
        # recorded per publish + per named subscriber when a trace is active
        self.tracer = None
        # device-budget trackers (JunctionDeviceStats) used by the fused
        # ingest path: step dispatch time, h2d bytes/chunks, sync stalls
        self.device_stats = None
        # pipelined-ingest stage budget (PipelineStats): encode/h2d/dispatch/
        # drain histograms + the pipeline.occupancy overlap gauge
        self.pipeline_stats = None
        # continuous profiler (observability/profiler.py): per-chunk stage
        # waterfalls + compile telemetry for the fused chunk program; both
        # None (one attribute check) when statistics are off
        self.profiler = None
        self.compile_telemetry = None
        # flight recorder (observability.flight.FlightRecorder): bounded
        # ring of the last N events through this junction, opt-in via
        # @flightRecorder(size='N') / SIDDHI_TPU_FLIGHT=N; None = one
        # attribute check on the hot path
        self.flight = None
        # lineage arena (observability.lineage.LineageArena): stamps every
        # valid CURRENT event with a monotonically increasing seq id and
        # keeps the last N decodable, opt-in via @app:lineage; None = one
        # attribute check on the hot path (same contract as flight)
        self.lineage = None
        # black-box incident ring (observability.blackbox.BlackboxRing):
        # seq-stamped ring of the last events through this junction,
        # opt-in via @app:blackbox; None = one attribute check on the hot
        # path (same contract as flight/lineage). on_incident is the
        # recorder's trigger hook — called with (trigger, detail) on
        # dispatch failures and unguarded crashes.
        self.blackbox = None
        self.on_incident: Callable[[str, str], None] | None = None
        # user hook for subscriber failures (reference: the pluggable
        # Disruptor ExceptionHandler, SiddhiAppRuntime.java:664)
        self.exception_handler: Callable[[Exception], None] | None = None
        # supervisor health signal (core/supervision.AppHealth.mark_fatal):
        # called with (exc, who) on UNGUARDED dispatch failures and worker
        # errors so manager.supervise() can restart the app; None when the
        # app is not supervised (one attribute check)
        self.on_fatal: Callable[[Exception, str], None] | None = None
        # @OnError policy (reference: StreamJunction.handleError + OnErrorAction):
        # None propagates to the sender; 'LOG' logs and drops the failing
        # batch; 'STREAM' redirects it (plus the error) to fault_junction;
        # 'STORE' spills it to the manager's ErrorStore via error_store_fn
        self.fault_policy: str | None = None
        self.fault_junction: "StreamJunction | None" = None
        self.error_store_fn: Callable[[], object] | None = None
        self.app_name: str = ""
        # churn ingress gate (core/churn.IngressGate): when set, input
        # handlers buffer (hold) or forward their sends instead of
        # publishing — the redeploy swap window and the paused replay mode
        # ride this. None = one attribute check on the ingest path.
        self.ingress_gate = None

    def enable_flight(self, size: int) -> None:
        """Attach a flight recorder of the last `size` events. Idempotent
        for an unchanged size: re-arming (e.g. the annotation resolving to
        the same ring the SIDDHI_TPU_FLIGHT env already applied) must not
        allocate a second arena and discard the recorded history."""
        if self.flight is not None and self.flight.size == int(size):
            return
        from siddhi_tpu.observability.flight import FlightRecorder

        self.flight = FlightRecorder(self.schema, self.interner, size)

    def enable_lineage(self, size: int) -> None:
        """Attach a lineage arena stamping + retaining the last `size`
        CURRENT events. Idempotent for an unchanged size (the recorded
        seq counter must survive re-arming)."""
        if self.lineage is not None and self.lineage.size == int(size):
            return
        from siddhi_tpu.observability.lineage import LineageArena

        self.lineage = LineageArena(self.schema, self.interner, size)

    def enable_blackbox(self, size: int, counter) -> None:
        """Attach a black-box incident ring of the last `size` events,
        seq-stamped from the app-wide arrival `counter`. Idempotent for an
        unchanged size (recorded history must survive re-arming)."""
        if self.blackbox is not None and self.blackbox.size == int(size):
            return
        from siddhi_tpu.observability.blackbox import BlackboxRing

        self.blackbox = BlackboxRing(self.schema, self.interner, size, counter)

    def describe_state(self) -> dict:
        """Cheap live-state snapshot (no device reads): queue depth, wiring,
        async worker health, fused/pipeline engagement, flight ring."""
        d: dict = {
            "queue_depth": self.queued(),
            "subscribers": list(self.subscriber_names),
            "callbacks": len(self.stream_callbacks),
            "batch_size": self.batch_size,
        }
        if self.is_async:
            workers = getattr(self, "_workers", [])
            d["async"] = {
                "workers": len(workers),
                "workers_alive": sum(1 for t in workers if t.is_alive()),
                "native_ring": getattr(self, "_ring", None) is not None,
            }
        if self.fault_policy is not None:
            d["on_error"] = self.fault_policy
        fi = self.fused_ingest
        if fi is not None:
            d["pipeline"] = fi.describe_state()
        if self.flight is not None:
            d["flight"] = self.flight.describe_state()
        if self.lineage is not None:
            d["lineage"] = self.lineage.describe_state()
        if self.blackbox is not None:
            d["blackbox"] = self.blackbox.describe_state()
        return d

    def subscribe(self, fn: Subscriber, name: str | None = None) -> None:
        """`name` labels this subscriber in error attribution and trace spans
        (e.g. 'query.q'); unnamed subscribers get a positional label."""
        self.subscribers.append(fn)
        self.subscriber_names.append(
            name if name else f"subscriber{len(self.subscribers) - 1}"
        )

    def unsubscribe(self, name: str) -> int:
        """Remove every subscriber registered under `name` (hot undeploy,
        core/churn.py). Caller holds the app process lock, so no fan-out
        can be mid-iteration over the lists. Returns how many were
        removed."""
        removed = 0
        with self.lock:
            keep = [
                (fn, n)
                for fn, n in zip(self.subscribers, self.subscriber_names)
                if n != name
            ]
            removed = len(self.subscribers) - len(keep)
            if removed:
                self.subscribers = [fn for fn, _n in keep]
                self.subscriber_names = [n for _fn, n in keep]
        return removed

    def add_stream_callback(self, fn: Callable, name: str | None = None) -> None:
        self.stream_callbacks.append(fn)
        self.stream_callback_names.append(
            name if name else f"callback{len(self.stream_callbacks) - 1}"
        )

    # ---- @async ingress (reference: StreamJunction.java:262-298 Disruptor
    # ring + StreamHandler batching into EventExchangeHolders) --------------

    def enable_async(
        self, buffer_size: int = 1024, workers: int = 1, batch_max: int | None = None
    ) -> None:
        import queue

        # a packed batch can never exceed the junction's device batch shape
        self._batch_max = min(
            int(batch_max) if batch_max else self.batch_size, self.batch_size
        )
        self._async_stop = threading.Event()
        self._workers = []
        self._ring = None
        from siddhi_tpu.core.types import AttrType

        if all(t is not AttrType.OBJECT for _, t in self.schema.attrs):
            # native lock-free ring (C++, the Disruptor analog); values ride
            # as doubles — exact for f32/f64/bool/interned-string ids and for
            # integers up to 2^53
            try:
                from siddhi_tpu.native import NativeIngressRing

                # +1 payload lane carries the per-row `now` clock value
                self._ring = NativeIngressRing(
                    int(buffer_size), len(self.schema.attrs) + 1
                )
            except Exception:
                self._ring = None  # no toolchain: python queue fallback
        if self._ring is None:
            self._queue = queue.Queue(maxsize=int(buffer_size))
        if self._ring is not None:
            workers = 1  # the native ring is single-consumer (MPSC)
        for _ in range(max(1, int(workers))):
            t = threading.Thread(
                target=self._drain_ring if self._ring is not None else self._drain,
                daemon=True,
            )
            t.start()
            self._workers.append(t)
        self.is_async = True

    def _encode_row(self, row) -> list[float]:
        from siddhi_tpu.core.types import AttrType, null_value

        out = []
        for v, (_n, t) in zip(row, self.schema.attrs):
            if t in (AttrType.STRING, AttrType.OBJECT):
                out.append(float(self.interner.intern(v)))
            elif v is None:
                nv = null_value(t)
                out.append(float(nv) if nv is not None else float("nan"))
            else:
                out.append(float(v))
        return out

    def _drain_ring(self) -> None:
        import numpy as np

        from siddhi_tpu.core.types import PHYSICAL_DTYPE

        dtypes = [np.dtype(PHYSICAL_DTYPE[t]) for _n, t in self.schema.attrs]
        names = self.schema.attr_names
        while not self._async_stop.is_set():
            # fault-injection site `drain_worker` (testing/faults.py):
            # OUTSIDE the poison-batch guard, so an injected fault kills the
            # worker thread — the "drain worker death" failure mode the
            # supervisor's health probe detects
            if _faults.ACTIVE is not None:
                _faults.ACTIVE.check("drain_worker", self.schema.stream_id)
            try:
                ring = self._ring
                if ring is None:
                    return
                ts, rows = ring.pop_batch(self._batch_max)
                if ts.shape[0] == 0:
                    self._async_stop.wait(0.001)
                    continue
                cols = {
                    n: rows[:, j].astype(dt)
                    for j, (n, dt) in enumerate(zip(names, dtypes))
                }
                batch = self.schema.to_batch_cols(
                    ts, cols, self.interner, capacity=self.batch_size
                )
                # the trailing payload lane carries the send-time clock
                self.publish_batch(batch, int(rows[-1, -1]))
            except Exception as e:
                self._on_worker_error(e, "async ring worker")

    def queued(self) -> int:
        ring = getattr(self, "_ring", None)
        if ring is not None:
            return ring.size()
        q = getattr(self, "_queue", None)
        return q.qsize() if q is not None else 0

    def _drain(self) -> None:
        import queue as _q

        while not self._async_stop.is_set():
            try:
                item = self._queue.get(timeout=0.1)
            except _q.Empty:
                continue
            # fault-injection site `drain_worker`: outside the poison-batch
            # guard — an injected fault KILLS the worker thread (the failure
            # mode the supervisor's health probe watches for), unlike a
            # poison batch which _on_worker_error survives
            if _faults.ACTIVE is not None:
                _faults.ACTIVE.check("drain_worker", self.schema.stream_id)
            ts_list, rows, now = [item[0]], [item[1]], item[2]
            # opportunistically batch up to batch_max (reference:
            # batch.size.max on the Disruptor consumer)
            while len(rows) < self._batch_max:
                try:
                    nxt = self._queue.get_nowait()
                except _q.Empty:
                    break
                ts_list.append(nxt[0])
                rows.append(nxt[1])
                now = nxt[2]
            try:
                batch = self.schema.to_batch(
                    ts_list, rows, self.interner, capacity=self.batch_size
                )
                self.publish_batch(batch, now)
            except Exception as e:  # a poisoned batch must not kill the worker
                self._on_worker_error(e, "async worker")

    def _on_worker_error(self, exc: Exception, who: str) -> None:
        """A poison batch (bad arity, un-packable value, downstream explosion
        that escaped per-subscriber guards) must not kill the drain worker:
        log, notify the app's exception handler, count it, and keep draining."""
        import logging
        import traceback

        logging.getLogger(__name__).error(
            "%s for stream '%s' dropped a batch:\n%s",
            who, self.schema.stream_id, traceback.format_exc(),
        )
        if self.on_error_stats is not None:
            self.on_error_stats(1)
        oi = self.on_incident
        if (
            oi is not None
            and self.exception_handler is None
            and self.fault_policy is None
        ):
            # unowned worker poison = crash incident (same ownership rule
            # as the supervisor health signal below)
            oi(
                "crash",
                f"{who} for stream '{self.schema.stream_id}': "
                f"{type(exc).__name__}: {exc}",
            )
        nf = self.on_fatal
        if (
            nf is not None
            and self.exception_handler is None
            and self.fault_policy is None
        ):
            # supervised apps treat a poisoned worker as a health signal —
            # but only when NOBODY owns the failure: with an exception
            # handler or an @OnError policy configured, the operator chose
            # handle-and-continue, and restarting would roll state back
            # over a handled poison batch. This also matters on the replay
            # path: failure_ownership is thread-local, so a poison entry
            # replayed into an @async stream fails HERE on the drain
            # worker thread, and an unconditional flag would put a
            # supervised app into a restart->replay->crash loop over one
            # bad stored entry.
            nf(exc, who)
        handler = self.exception_handler
        if handler is not None:
            try:
                handler(exc)
            except Exception:
                logging.getLogger(__name__).exception(
                    "exception handler for stream '%s' raised",
                    self.schema.stream_id,
                )

    def stop_async(self) -> None:
        ev = getattr(self, "_async_stop", None)
        if ev is None:
            return
        # drain what's left before stopping
        import time as _time

        t0 = _time.monotonic()
        while self.queued() > 0 and _time.monotonic() - t0 < 5.0:
            _time.sleep(0.01)
        dropped = self.queued()
        if dropped:
            import logging

            logging.getLogger(__name__).error(
                "async shutdown for stream '%s' timed out with %d events "
                "still queued — they were dropped",
                self.schema.stream_id, dropped,
            )
        # leave the async path BEFORE tearing the ring down so late sends fall
        # through to the synchronous publish path instead of crashing
        self.is_async = False
        ring = getattr(self, "_ring", None)
        self._ring = None  # detach first: queued()/producers now see None
        ev.set()
        joined = True
        for t in self._workers:
            if t is not threading.current_thread():
                t.join(timeout=2.0)
                joined = joined and not t.is_alive()
        self._workers = []
        if ring is not None and joined:
            # only free the native arena once no thread can still touch it;
            # an unjoined worker leaks the ring to the GC instead of UAF-ing
            ring.close()

    # ---- publishing ------------------------------------------------------

    def publish_batch(self, batch: EventBatch, now: int) -> None:
        """Fan a device batch out to all subscribers (already this stream's schema)."""
        pl = self.process_lock
        if pl is None:
            return self._publish_batch(batch, now)
        # hold the app's snapshot barrier across the WHOLE fan-out: each
        # subscriber's receive takes the same RLock (nested, free), but
        # without the outer hold a checkpoint could land BETWEEN two
        # queries' dispatches of one batch — a torn cross-query snapshot
        # that diverges on restore+refeed (the chaos harness caught this).
        # Acquired BEFORE self.lock so lock order is process -> junction
        # on every path (insert-into chains re-enter under the same RLock)
        with pl:
            return self._publish_batch(batch, now)

    def _publish_batch(self, batch: EventBatch, now: int) -> None:
        with self.lock:
            fl = self.flight
            if fl is not None:
                fl.record_batch(batch)
            bb = self.blackbox
            if bb is not None:
                bb.record_batch(batch)
            la = self.lineage
            seq_range = None
            if la is not None:
                # stamp the batch's valid CURRENT rows with seq ids; the
                # range is read under this same lock by the @OnError STORE
                # path (la.last_range) and attached to the publish span
                seq_range = la.record_batch(batch)
                if seq_range[1]:
                    from siddhi_tpu.observability.lineage import (
                        current_publisher,
                    )

                    pub = current_publisher()
                    if pub is not None:
                        # per-publish producer capture: this stamp came
                        # from a recorded query's insert — note which, so
                        # multi-producer streams resolve seq -> producer.
                        # pub_base: the recorder counted this batch's
                        # published records in observe() (receive runs
                        # before the publish), so the range starts
                        # n records back from its pub_count.
                        qid, rec = pub
                        la.note_producer(
                            seq_range[0], seq_range[1], qid,
                            max(rec.pub_count - seq_range[1], 0),
                        )
            n_valid = -1
            if self.on_publish_stats is not None:
                n_valid = int(np.asarray(batch.valid).sum())
                self.on_publish_stats(n_valid)
            tr = self.tracer
            root = (
                tr.start_span(f"stream.{self.schema.stream_id}", n_valid)
                if tr is not None
                else None
            )
            if root is not None and seq_range is not None and seq_range[1]:
                tr.annotate(root, "lineage_seq", list(seq_range))
            try:
                guarded = (
                    self.exception_handler is not None or self.fault_policy is not None
                )
                routed = self._fan_out(
                    zip(self.subscribers, self.subscriber_names),
                    batch, now, tr, n_valid, guarded,
                )
                if self.stream_callbacks:
                    try:
                        events = self.schema.from_batch(batch, self.interner)
                    except Exception as e:
                        if not guarded:
                            raise
                        self._on_dispatch_error(batch, now, e, routed)
                        return
                    if events:
                        rows = [(ts, data) for ts, kind, data in events]
                        for i, cb in enumerate(self.stream_callbacks):
                            sp = (
                                tr.start_span(
                                    self.stream_callback_names[i], len(rows)
                                )
                                if tr is not None
                                else None
                            )
                            try:
                                if not guarded:
                                    cb(rows)
                                else:
                                    try:
                                        cb(rows)
                                    except Exception as e:
                                        routed |= self._on_dispatch_error(
                                            batch, now, e, routed,
                                            subscriber=self.stream_callback_names[i],
                                        )
                            finally:
                                if sp is not None:
                                    tr.end_span(sp)
            finally:
                if root is not None:
                    tr.end_span(root)

    def _fan_out(
        self, pairs, batch: EventBatch, now: int, tr, n_valid: int,
        guarded: bool,
    ) -> bool:
        """Dispatch one batch to [(fn, name)] pairs — THE per-subscriber
        loop, shared by publish_batch (all subscribers) and dispatch_subset
        (the fused group engine's residual subset), so failure-policy and
        tracing semantics cannot drift between the two paths. Returns the
        routed flag: one STREAM/STORE routing per batch even when several
        subscribers fail on it — fault consumers must not double-count a
        failure."""
        routed = False
        for fn, name in pairs:
            sp = tr.start_span(name, n_valid) if tr is not None else None
            try:
                try:
                    # fault-injection site `junction_dispatch` (testing/
                    # faults.py): inside the dispatch so an injected
                    # failure rides the exact path a real subscriber
                    # explosion takes — the guarded branch routes it per
                    # the failure policy, the unguarded branch propagates
                    # it to the sender
                    if _faults.ACTIVE is not None:
                        _faults.ACTIVE.check(
                            "junction_dispatch",
                            f"{self.schema.stream_id}:{name}",
                        )
                    fn(batch, now)
                except Exception as e:
                    if not guarded:
                        # unguarded: freeze a crash incident and raise a
                        # fatal health signal for the supervisor, then on
                        # to the sender
                        oi = self.on_incident
                        if oi is not None:
                            oi(
                                "crash",
                                f"stream '{self.schema.stream_id}' dispatch "
                                f"to {name}: {type(e).__name__}: {e}",
                            )
                        nf = self.on_fatal
                        if nf is not None:
                            nf(e, f"dispatch to {name}")
                        raise
                    routed |= self._on_dispatch_error(  # user-owned policy
                        batch, now, e, routed, subscriber=name,
                    )
            finally:
                if sp is not None:
                    tr.end_span(sp)
        return routed

    def dispatch_subset(self, batch: EventBatch, now: int, subset) -> None:
        """Fan one batch out to an explicit [(fn, name)] subscriber subset —
        the fused group engine's residual path (core/ingest.py
        `_residual_dispatch`): the plan's SA124-blocked consumers get every
        micro-batch per batch, exactly as publish_batch would run them.
        Throughput stats and the flight ring are NOT touched here — the
        fused commit already counted and recorded these events; recording
        again would double them. Per-subscriber failure policy and trace
        spans ride the same _fan_out loop publish_batch uses."""
        pl = self.process_lock
        if pl is None:
            return self._dispatch_subset(batch, now, subset)
        with pl:  # same snapshot-barrier hold as publish_batch
            return self._dispatch_subset(batch, now, subset)

    def _dispatch_subset(self, batch: EventBatch, now: int, subset) -> None:
        with self.lock:
            tr = self.tracer
            n_valid = (
                int(np.asarray(batch.valid).sum()) if tr is not None else -1
            )
            guarded = (
                self.exception_handler is not None
                or self.fault_policy is not None
            )
            self._fan_out(subset, batch, now, tr, n_valid, guarded)

    def _on_dispatch_error(
        self,
        batch: EventBatch,
        now: int,
        exc: Exception,
        routed: bool = False,
        subscriber: str | None = None,
    ) -> bool:
        """Apply the stream's failure policy to one failed dispatch; returns
        True when the batch's events were routed (fault stream / error store).
        With `routed` set, the handler/stats/log still run for this failure
        but the payload is not re-routed. The batch never propagates to the
        sender once a handler or @OnError policy owns the failure
        (reference: StreamJunction.handleError:390-404)."""
        import logging

        log = logging.getLogger(__name__)
        oi = self.on_incident
        if oi is not None:  # black box: a dispatch failure is an incident
            oi(
                "dispatch_error",
                f"stream '{self.schema.stream_id}'"
                + (f" subscriber {subscriber}" if subscriber else "")
                + f": {type(exc).__name__}: {exc}",
            )
        if self.on_error_stats is not None:
            self.on_error_stats(1)
        factory = self.error_stats_factory
        if factory is not None and subscriber is not None:
            add = self._sub_error_stats.get(subscriber)
            if add is None:
                add = self._sub_error_stats[subscriber] = factory(subscriber)
            add(1)
        if self.exception_handler is not None:
            try:
                self.exception_handler(exc)
            except Exception:
                log.exception(
                    "exception handler for stream '%s' raised", self.schema.stream_id
                )
        policy = self.fault_policy
        if policy is None:
            return False  # handler-only: existing set_exception_handler semantics
        if policy == "LOG":
            log.error(
                "stream '%s': dropping a failed batch (@OnError action='LOG'): %s",
                self.schema.stream_id, exc, exc_info=exc,
            )
            return False
        if routed:
            return False  # another subscriber already routed this batch
        from siddhi_tpu.core.event import KIND_CURRENT, KIND_EXPIRED

        try:
            events = self.schema.from_batch(batch, self.interner)
        except Exception:
            log.exception(
                "stream '%s': could not decode a failed batch for @OnError "
                "routing; the batch was dropped", self.schema.stream_id,
            )
            return False
        # only payload rows route onward: TIMER/RESET rows are synthetic
        # all-null scheduler artifacts, not user events
        events = [e for e in events if e[1] in (KIND_CURRENT, KIND_EXPIRED)]
        if policy == "STREAM":
            fj = self.fault_junction
            if fj is None or not events:
                return False
            err = f"{type(exc).__name__}: {exc}"
            try:
                # publish per-chunk with the kind lane preserved — an EXPIRED
                # row must not resurface on !S as a CURRENT event
                cap = fj.batch_size
                for ofs in range(0, len(events), cap):
                    chunk = events[ofs : ofs + cap]
                    fb = fj.schema.to_batch(
                        [ts for ts, _k, _d in chunk],
                        [tuple(d) + (err,) for _ts, _k, d in chunk],
                        fj.interner,
                        capacity=cap,
                        kinds=[k for _ts, k, _d in chunk],
                    )
                    fj.publish_batch(fb, now)
            except Exception:
                log.exception(
                    "fault stream '%s' dispatch failed; the batch was dropped",
                    fj.schema.stream_id,
                )
            return True
        if policy == "STORE":
            from siddhi_tpu.core.error_store import ORIGIN_STREAM, make_entry

            store = self.error_store_fn() if self.error_store_fn is not None else None
            if store is None:
                log.error(
                    "stream '%s': @OnError action='STORE' but no error store "
                    "is available; the batch was dropped", self.schema.stream_id,
                )
                return False
            if not events:
                return False
            # replay re-injects through the input handler, i.e. as CURRENT
            # events; EXPIRED rows are recorded for inspection all the same
            entry = make_entry(
                self.app_name, ORIGIN_STREAM, self.schema.stream_id, exc,
                events=[(ts, tuple(d)) for ts, _k, d in events],
            )
            if self.lineage is not None:
                # contributing seq ids: the failing batch was stamped at
                # the top of this publish (same junction lock) — last_range
                # is exactly its rows
                base, n = self.lineage.last_range
                if n:
                    entry.lineage = {
                        "stream": self.schema.stream_id,
                        "seq_lo": base,
                        "seq_hi": base + n - 1,
                    }
            if self.flight is not None:
                # black-box dump: the last-N events through this junction
                # BEFORE the failure, decoded host-side (the failing batch's
                # own rows are already in the ring — it was recorded at
                # publish time)
                try:
                    entry.flight = self.flight.events()
                except Exception:
                    log.exception(
                        "stream '%s': flight-recorder dump failed",
                        self.schema.stream_id,
                    )
            store.store(entry)
            return True
        return False

    is_async = False

    def send_rows(
        self,
        timestamps: Sequence[int],
        rows: Sequence[Sequence[Any]],
        now: int | None = None,
    ) -> None:
        """Pack host rows and publish, chunking to the junction batch size.
        In @async mode rows enqueue into the ingress ring (blocking when full
        = back-pressure) and a worker thread batches + publishes."""
        if self.is_async:
            ring = getattr(self, "_ring", None)
            if ring is not None:
                import time as _time

                stop = self._async_stop
                for ts, row in zip(timestamps, rows):
                    enc = self._encode_row(row)
                    enc.append(float(now if now is not None else ts))
                    while not ring.push(ts, enc):
                        if stop.is_set():
                            return  # shutting down: drop instead of hanging
                        _time.sleep(0.0005)  # back-pressure without a hot spin
            else:
                for ts, row in zip(timestamps, rows):
                    self._queue.put((ts, tuple(row), now if now is not None else ts))
            return
        n = len(rows)
        for ofs in range(0, max(n, 1), self.batch_size):
            ts_chunk = list(timestamps[ofs : ofs + self.batch_size])
            row_chunk = list(rows[ofs : ofs + self.batch_size])
            if not row_chunk:
                return
            batch = self.schema.to_batch(
                ts_chunk, row_chunk, self.interner, capacity=self.batch_size
            )
            self.publish_batch(batch, now if now is not None else (ts_chunk[-1] if ts_chunk else 0))


class InputHandler:
    """Reference: stream/input/InputHandler.java:27-68."""

    def __init__(self, junction: StreamJunction, clock: Callable[[], int]):
        self.junction = junction
        self.clock = clock

    def send(self, data: Sequence[Any], timestamp: int | None = None) -> None:
        ts = timestamp if timestamp is not None else self.clock()
        g = self.junction.ingress_gate
        if g is not None and g.intercept(
            "rows", ([ts], [tuple(data)], self.clock()), 1
        ):
            return
        self.junction.send_rows([ts], [tuple(data)], now=self.clock())

    def send_many(
        self, rows: Sequence[Sequence[Any]], timestamps: Sequence[int] | None = None
    ) -> None:
        if timestamps is None:
            t = self.clock()
            timestamps = [t] * len(rows)
        timestamps = list(timestamps)
        rows = [tuple(r) for r in rows]
        g = self.junction.ingress_gate
        if g is not None and g.intercept(
            "rows", (timestamps, rows, self.clock()), len(rows)
        ):
            return
        self.junction.send_rows(timestamps, rows, now=self.clock())

    def send_columns(
        self,
        timestamps: np.ndarray,
        cols: dict[str, np.ndarray],
        now: int | None = None,
    ) -> None:
        """High-throughput columnar ingest: one device batch per junction
        batch-size chunk, no per-row Python work (the analog of the reference's
        @async batched Disruptor path, StreamJunction.java:262-298).

        All-numeric chunks (pre-interned string ids included) ride the packed
        codec: ONE contiguous host->device transfer per batch, bitcast-split
        on device — the dominant win when the chip is behind a network tunnel.
        """
        j = self.junction
        n = len(timestamps)
        if now is None:
            now = self.clock()  # same wall-clock default as send/send_many
        g = j.ingress_gate
        if g is not None and g.intercept("cols", (timestamps, cols, now), n):
            return
        numeric = all(np.asarray(v).dtype.kind not in "OUS" for v in cols.values())
        fi = j.fused_ingest
        if numeric and fi is not None and fi.try_send(timestamps, cols, now):
            return
        if numeric:
            encode, decode = j.schema.packed_codec(j.batch_size)
            prof = j.profiler
            for ofs in range(0, n, j.batch_size):
                end = min(ofs + j.batch_size, n)
                m = end - ofs
                # per-batch waterfall (observability/profiler.py): encode +
                # dispatch walls here; the query step adds device/readback
                # sub-stages through the profiler's thread-local context.
                # wf is None when statistics are off/disabled (one check).
                wf = prof.begin(j.schema.stream_id, m) if prof is not None else None
                t0 = time.perf_counter_ns() if wf is not None else 0
                buf = encode(
                    timestamps[ofs:end],
                    {k: v[ofs:end] for k, v in cols.items()},
                    m,
                )
                batch = decode(buf, np.int32(m))
                if wf is None:
                    j.publish_batch(batch, now)
                    continue
                wf.stage("encode", time.perf_counter_ns() - t0)
                prof.tls_begin(wf)
                t0 = time.perf_counter_ns()
                try:
                    j.publish_batch(batch, now)
                finally:
                    wf.stage("dispatch", time.perf_counter_ns() - t0)
                    prof.tls_end()
                    prof.end(wf)
            return
        for ofs in range(0, n, j.batch_size):
            ts_chunk = timestamps[ofs : ofs + j.batch_size]
            chunk = {k: v[ofs : ofs + j.batch_size] for k, v in cols.items()}
            batch = j.schema.to_batch_cols(
                ts_chunk, chunk, j.interner, capacity=j.batch_size
            )
            j.publish_batch(batch, now)


def system_clock_ms() -> int:
    return int(time.time() * 1000)
