"""Stream junctions and input handlers — host-side event routing.

Reference: stream/StreamJunction.java:58-404 (per-stream pub/sub fan-out) and
stream/input/InputManager.java / InputHandler.java. The device does all per-event
math; the junction packs host events into fixed-capacity columnar micro-batches
and fans them out to subscriber step functions. Synchronous dispatch mirrors the
reference's default pass-through mode; @async batching rides the same path via
send_batch.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Sequence

import numpy as np

from siddhi_tpu.core.event import EventBatch, StreamSchema
from siddhi_tpu.core.types import InternTable

# subscriber: fn(batch: EventBatch, now_ms: int) -> None
Subscriber = Callable[[EventBatch, int], None]


class StreamJunction:
    def __init__(
        self,
        schema: StreamSchema,
        interner: InternTable,
        batch_size: int = 64,
    ):
        self.schema = schema
        self.interner = interner
        self.batch_size = batch_size
        self.subscribers: list[Subscriber] = []
        self.stream_callbacks: list[Callable] = []
        # RLock: a query may legally insert into its own input stream
        # (reference allows self-feeding junctions); recursion stays on-thread
        self.lock = threading.RLock()
        self.on_publish_stats: Callable[[int], None] | None = None

    def subscribe(self, fn: Subscriber) -> None:
        self.subscribers.append(fn)

    def add_stream_callback(self, fn: Callable) -> None:
        self.stream_callbacks.append(fn)

    # ---- publishing ------------------------------------------------------

    def publish_batch(self, batch: EventBatch, now: int) -> None:
        """Fan a device batch out to all subscribers (already this stream's schema)."""
        with self.lock:
            if self.on_publish_stats is not None:
                self.on_publish_stats(int(np.asarray(batch.valid).sum()))
            for fn in self.subscribers:
                fn(batch, now)
            if self.stream_callbacks:
                events = self.schema.from_batch(batch, self.interner)
                if events:
                    rows = [(ts, data) for ts, kind, data in events]
                    for cb in self.stream_callbacks:
                        cb(rows)

    def send_rows(
        self,
        timestamps: Sequence[int],
        rows: Sequence[Sequence[Any]],
        now: int | None = None,
    ) -> None:
        """Pack host rows and publish, chunking to the junction batch size."""
        n = len(rows)
        for ofs in range(0, max(n, 1), self.batch_size):
            ts_chunk = list(timestamps[ofs : ofs + self.batch_size])
            row_chunk = list(rows[ofs : ofs + self.batch_size])
            if not row_chunk:
                return
            batch = self.schema.to_batch(
                ts_chunk, row_chunk, self.interner, capacity=self.batch_size
            )
            self.publish_batch(batch, now if now is not None else (ts_chunk[-1] if ts_chunk else 0))


class InputHandler:
    """Reference: stream/input/InputHandler.java:27-68."""

    def __init__(self, junction: StreamJunction, clock: Callable[[], int]):
        self.junction = junction
        self.clock = clock

    def send(self, data: Sequence[Any], timestamp: int | None = None) -> None:
        ts = timestamp if timestamp is not None else self.clock()
        self.junction.send_rows([ts], [tuple(data)], now=self.clock())

    def send_many(
        self, rows: Sequence[Sequence[Any]], timestamps: Sequence[int] | None = None
    ) -> None:
        if timestamps is None:
            t = self.clock()
            timestamps = [t] * len(rows)
        self.junction.send_rows(list(timestamps), [tuple(r) for r in rows], now=self.clock())

    def send_columns(
        self,
        timestamps: np.ndarray,
        cols: dict[str, np.ndarray],
        now: int | None = None,
    ) -> None:
        """High-throughput columnar ingest: one device batch per junction
        batch-size chunk, no per-row Python work (the analog of the reference's
        @async batched Disruptor path, StreamJunction.java:262-298)."""
        j = self.junction
        n = len(timestamps)
        if now is None:
            now = self.clock()  # same wall-clock default as send/send_many
        for ofs in range(0, n, j.batch_size):
            ts_chunk = timestamps[ofs : ofs + j.batch_size]
            chunk = {k: v[ofs : ofs + j.batch_size] for k, v in cols.items()}
            batch = j.schema.to_batch_cols(
                ts_chunk, chunk, j.interner, capacity=j.batch_size
            )
            j.publish_batch(batch, now)


def system_clock_ms() -> int:
    return int(time.time() * 1000)
