"""Fused columnar ingest: K micro-batches per transfer + dispatch.

Reference analog: the @async Disruptor consumer batching events into
EventExchangeHolders before the query chain runs them
(stream/StreamJunction.java:262-298, util/event/handler/StreamHandler.java) —
the TPU-shaped version aggregates K whole micro-batches into ONE contiguous
host buffer, ONE host->device transfer, and ONE jitted dispatch whose
`lax.scan` runs the junction's entire subscriber fan-out over the K batches
with carried state.

Why it exists: behind a network tunnel each transfer/dispatch pays a fixed
relay overhead (measured 2.5-9 ms once the relay leaves its speculative fast
mode), so per-micro-batch dispatch caps throughput regardless of device
speed. Fusing K=32 batches amortizes that overhead 32x and keeps everything
else identical: the scan body decodes sub-batch k and runs the same
`_step_impl` chains the per-batch path runs, in the same order.

Engagement is conservative: the fused path is used only when nothing
host-side observes per-batch boundaries — no stream callbacks, no query
callbacks, no rate limiters, no scheduler-armed windows/patterns, no live
debugger, and the queries' insert targets have no consumers. Anything else
falls back to the per-batch path with identical semantics.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

import jax
import jax.lax as lax
import jax.numpy as jnp
import numpy as np


class FuseEndpoint:
    """One junction subscriber in fused form.

    impl_factory() must return a pure step
    `(state, tstates, batch, now) -> (state', tstates', out, aux)` — the same
    function object the per-batch jit wraps.
    """

    def __init__(
        self,
        qr,
        impl_factory: Callable[[], Callable],
        init_state: Callable[[int], object],
        latency_tracker=None,
    ):
        self.qr = qr
        self.impl_factory = impl_factory
        self.init_state = init_state
        self.latency_tracker = latency_tracker


def _needs_scheduler(qr) -> bool:
    ns = getattr(qr, "needs_scheduler", False)
    if isinstance(ns, dict):
        return any(ns.values())
    return bool(ns)


class FusedJunctionIngest:
    """Per-junction fused ingest engine (built at app start)."""

    def __init__(self, app, junction, endpoints, chunk_batches: int = 32):
        self.app = app
        self.junction = junction
        self.endpoints = list(endpoints)
        self.K = max(2, int(chunk_batches))
        self._fused = None
        self._disabled = False
        self._lock = threading.Lock()

    # ---- eligibility (cheap dynamic checks, every send) ------------------

    def eligible(self) -> bool:
        j = self.junction
        if j.is_async or j.stream_callbacks:
            return False
        if getattr(self.app, "_debugger", None) is not None:
            return False
        if len(j.subscribers) != len(self.endpoints):
            return False  # an unfused subscriber is attached
        for ep in self.endpoints:
            qr = ep.qr
            if ep.latency_tracker is not None:
                return False
            if getattr(qr, "rate_limiter", None) is not None:
                return False
            if getattr(qr, "query_callbacks", None):
                return False
            if _needs_scheduler(qr) or getattr(qr, "host_next_timer", None):
                return False
            tj = getattr(qr, "_insert_target_junction", None)
            if tj is not None and (
                tj.subscribers or tj.stream_callbacks
                or tj.on_publish_stats is not None
            ):
                return False
        return True

    # ---- device program --------------------------------------------------

    def _build(self):
        B = self.junction.batch_size
        schema = self.junction.schema
        # projected wire: ship only attributes some subscriber reads
        used: set | None = set()
        for ep in self.endpoints:
            ua = getattr(ep.qr, "used_attrs", None)
            if ua is None:
                used = None  # unknown/select * — keep everything
                break
            used |= ua
        self._keep = (
            None if used is None
            else frozenset(n for n in schema.attr_names if n in used)
        )
        _encode, decode, self._wire_bytes = schema.wire_codec(B, self._keep)
        impls = [ep.impl_factory() for ep in self.endpoints]

        def fused(states, tstates, wire, counts, bases, now):
            def body(carry, xs):
                sts, tst = carry
                batch = decode(xs[0], xs[1], xs[2])
                new_states = []
                auxes = []
                for impl, st in zip(impls, sts):
                    st2, tst, _out, aux = impl(st, tst, batch, now)
                    new_states.append(st2)
                    auxes.append(
                        tuple(
                            jnp.asarray(v).astype(bool).any()
                            for k, v in sorted(aux.items())
                            if k != "next_timer"
                        )
                    )
                return (tuple(new_states), tst), tuple(auxes)

            (states, tstates), aux_stack = lax.scan(
                body, (states, tstates), (wire, counts, bases)
            )
            aux_red = tuple(
                tuple(v.any() for v in a) for a in aux_stack
            )
            return states, tstates, aux_red

        # donate the per-endpoint states (exclusively owned); tstates may
        # alias read-only findables shared with other runtimes — not donated
        self._fused = jax.jit(fused, donate_argnums=(0,))
        self._aux_keys = [self._probe_aux_keys(i) for i in range(len(impls))]

    # ---- host side -------------------------------------------------------

    def try_send(self, timestamps, cols, now: int) -> bool:
        """Attempt fused ingest of the whole call. Returns False to make the
        caller fall back to the per-batch path."""
        n = len(timestamps)
        B = self.junction.batch_size
        # engage only when the call fills a decent fraction of a chunk —
        # shorter sends would pay a full K-iteration scan of mostly-empty
        # batches, slower than the per-batch path off the tunnel
        if n < max(2 * B, self.K * B // 2) or self._disabled or not self.eligible():
            return False
        with self._lock:
            if self._fused is None:
                try:
                    self._build()
                except Exception:
                    import logging

                    logging.getLogger(__name__).warning(
                        "fused ingest disabled for stream '%s' (build failed)",
                        self.junction.schema.stream_id, exc_info=True,
                    )
                    self._disabled = True
                    return False
        ts_arr = np.asarray(timestamps)
        if n and int(ts_arr.max()) - int(ts_arr.min()) >= (1 << 31):
            return False  # int32 ts-delta wire can't span >24 days per call
        encode, _decode, _nb = self.junction.schema.wire_codec(B, self._keep)

        app_lock = self.app._process_lock
        K = self.K
        for c_off in range(0, n, K * B):
            c_end = min(c_off + K * B, n)
            bufs = []
            counts = np.zeros((K,), dtype=np.int32)
            bases = np.zeros((K,), dtype=np.int64)
            for k in range(K):
                lo = c_off + k * B
                hi = min(lo + B, c_end)
                m = max(hi - lo, 0)
                counts[k] = m
                if m > 0:
                    buf, base = encode(
                        ts_arr[lo:hi],
                        {kk: v[lo:hi] for kk, v in cols.items()},
                        m,
                    )
                    bufs.append(buf)
                    bases[k] = base
                else:
                    bufs.append(np.zeros_like(bufs[0]))
            wire = np.stack(bufs)  # [K, bytes]

            with app_lock:
                states = []
                for ep in self.endpoints:
                    if ep.qr.state is None:
                        ep.qr.state = ep.qr._fresh(ep.init_state(now))
                    states.append(ep.qr.state)
                tstates = {}
                ep_tids = []
                for ep in self.endpoints:
                    ts_ep = ep.qr._collect_table_states()
                    ep_tids.append(list(ts_ep))
                    tstates.update(ts_ep)
                try:
                    new_states, tstates, aux_red = self._fused(
                        tuple(states), tstates, wire,
                        counts, bases, np.int64(now),
                    )
                except Exception as e:
                    # the call donated the state buffers: they are gone either
                    # way, so reset to fresh state (lazily re-initialized on
                    # the next receive) instead of leaving every later send
                    # crashing on deleted arrays; then honor the junction's
                    # failure policy like the per-batch path does (which
                    # drops at most the failing batch and keeps going)
                    for ep in self.endpoints:
                        ep.qr.state = None
                    handler = self.junction.exception_handler
                    if handler is None:
                        raise
                    handler(e)
                    continue  # next chunk, like per-batch send_columns would
                for ep, st in zip(self.endpoints, new_states):
                    ep.qr.state = st
                for ep, tids in zip(self.endpoints, ep_tids):
                    ep.qr._writeback_table_states(
                        {tid: tstates[tid] for tid in tids}
                    )
            if self.junction.on_publish_stats is not None:
                self.junction.on_publish_stats(int(counts.sum()))
            for i, ep in enumerate(self.endpoints):
                flags = dict(zip(self._aux_keys[i], aux_red[i]))
                if flags:
                    ep.qr._warn_aux(flags)
        return True

    def _probe_aux_keys(self, i: int) -> list:
        """Sorted non-timer aux keys for endpoint i, discovered by tracing
        the impl's aux output structure once (abstract eval, no device)."""
        ep = self.endpoints[i]
        impl = ep.impl_factory()
        B = self.junction.batch_size
        schema = self.junction.schema
        batch = schema.empty_batch(B)
        st = ep.init_state(0)
        tst = {}
        for e2 in self.endpoints:
            tst.update(e2.qr._collect_table_states())
        closed = jax.eval_shape(
            lambda s, t, bb: impl(s, t, bb, np.int64(0))[3], st, tst, batch
        )
        return sorted(k for k in closed.keys() if k != "next_timer")
