"""Fused columnar ingest: K micro-batches per transfer + dispatch.

Reference analog: the @async Disruptor consumer batching events into
EventExchangeHolders before the query chain runs them
(stream/StreamJunction.java:262-298, util/event/handler/StreamHandler.java) —
the TPU-shaped version aggregates K whole micro-batches into ONE contiguous
host buffer, ONE host->device transfer, and ONE jitted dispatch whose
`lax.scan` runs the junction's entire subscriber fan-out over the K batches
with carried state.

Why it exists: behind a network tunnel each transfer/dispatch pays a fixed
relay overhead (measured 2.5-9 ms once the relay leaves its speculative fast
mode), so per-micro-batch dispatch caps throughput regardless of device
speed. Fusing K=32 batches amortizes that overhead 32x and keeps everything
else identical: the scan body decodes sub-batch k and runs the same
`_step_impl` chains the per-batch path runs, in the same order.

Engagement is conservative: the fused path is used only when nothing
host-side observes per-batch boundaries — no stream callbacks, no query
callbacks, no rate limiters, no scheduler-armed windows/patterns, no live
debugger, and the queries' insert targets have no consumers. Anything else
falls back to the per-batch path with identical semantics.

Chunk stages (encode -> h2d -> dispatch -> drain) run double-buffered by
default through core/pipeline.py: chunk N+1 is encoded into a pooled wire
buffer and device_put while chunk N's donated-state dispatch is in flight,
and deliver-mode readback+decode+callbacks run on a bounded background
drain worker in chunk order. `@pipeline(disable='true')` (or
SIDDHI_TPU_PIPELINE=0) restores the fully serial path; outputs and
delivery order are identical either way.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import jax
import jax.lax as lax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.event import WireNarrowMisfit
from siddhi_tpu.testing import faults as _faults


class FuseEndpoint:
    """One junction subscriber in fused form.

    impl_factory() must return a pure step
    `(state, tstates, batch, now) -> (state', tstates', out, aux)` — the same
    function object the per-batch jit wraps.
    """

    def __init__(
        self,
        qr,
        impl_factory: Callable[[], Callable],
        init_state: Callable[[int], object],
        latency_tracker=None,
    ):
        self.qr = qr
        self.impl_factory = impl_factory
        self.init_state = init_state
        # in fused mode per-batch markIn/markOut is impossible (K batches run
        # in one dispatch), so the tracker records the CHUNK dispatch wall
        # time instead — the engine's actual unit of processing latency here
        self.latency_tracker = latency_tracker


class _RebuildFailed(Exception):
    """Internal: a full-width rebuild after a narrow-wire misfit failed
    mid-pipelined-send; `cause` carries the original build error."""

    def __init__(self, cause: Exception):
        super().__init__(str(cause))
        self.cause = cause


def _needs_scheduler(qr) -> bool:
    ns = getattr(qr, "needs_scheduler", False)
    if isinstance(ns, dict):
        return any(ns.values())
    return bool(ns)


class FusedJunctionIngest:
    """Per-junction fused ingest engine (built at app start)."""

    def __init__(
        self,
        app,
        junction,
        endpoints,
        chunk_batches: int = 32,
        pipeline_enabled: bool = True,
        pipeline_depth: int = 2,
        component: str = None,
        residual=None,
        share_sets=None,
        plan_group=None,
        wire_spec=None,
        wire_enabled: bool = True,
    ):
        self.app = app
        self.junction = junction
        self.endpoints = list(endpoints)
        self.K = max(2, int(chunk_batches))
        # plan-driven group mode (core/fusion_exec.py): `residual` holds the
        # junction subscribers NOT in the fused group — after a fused send
        # commits, every micro-batch is re-dispatched to them per batch, so
        # blocked (SA124) queries keep byte-identical per-batch semantics
        self.component = component or (
            f"stream.{junction.schema.stream_id}.fused"
        )
        self.residual = list(residual or [])
        self.plan_group = plan_group
        # cross-query state sharing: each share set is a list of endpoint
        # indices whose filter+window chain states are provably identical —
        # the chunk program carries ONE canonical chain per set (the first
        # member's) and every member reads it (see _build / _pack_arg0)
        self.share_sets = [list(s) for s in (share_sets or []) if len(s) >= 2]
        self._share_of = {
            i: g for g, idxs in enumerate(self.share_sets) for i in idxs
        }
        self._share_leader = {
            g: idxs[0] for g, idxs in enumerate(self.share_sets)
        }
        # surface the sharing in each member's describe_state(): one ring,
        # refcounted across the set (observability/introspect.py), and arm
        # the unshare guard: EVERY per-batch entry point that can donate a
        # member's state funnels through QueryRuntime.receive (row sends,
        # non-numeric send_columns, insert-into publishes, timer fires), so
        # the guard there — under the same app process lock the fused
        # writeback aliases chains under — is the one sound split point
        for idxs in self.share_sets:
            qids = [
                getattr(self.endpoints[i].qr, "query_id", i) for i in idxs
            ]
            for i in idxs:
                self.endpoints[i].qr.shared_ring = {
                    "queries": qids,
                    "leader": qids[0],
                    "refcount": len(idxs),
                }
                self.endpoints[i].qr._unshare_guard = self._maybe_unshare
        # True once a fused dispatch wrote back aliased chain states: the
        # per-batch path donates per-query states independently, so any
        # fall-back first un-aliases follower chains (_maybe_unshare)
        self._aliased = False
        # achieved-dispatch accounting (vs the plan's n*K -> 1 prediction)
        self.chunks_dispatched = 0
        self.batches_fused = 0
        self.events_fused = 0
        self._fused = None
        self._fused_deliver = None
        self._disabled = False
        # wire encodings (core/wire.py): None = not chosen yet (decided at
        # the first engaged send: the static WireSpec's analyzer-chosen
        # encoders overlaid on the sampled narrow dtypes when enabled; {}
        # when wire encoding is off OR permanently after any misfit =
        # full-width wire)
        self._narrow = None
        self.wire_spec = wire_spec
        self.wire_enabled = bool(wire_enabled)
        self._lock = threading.Lock()
        # double-buffered chunk pipeline (core/pipeline.py): built lazily on
        # the first engaged send; senders serialize on _send_lock so the
        # pooled wire buffers and the drain queue see one producer
        self.pipeline_enabled = bool(pipeline_enabled)
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.pipeline = None
        self._send_lock = threading.Lock()
        self._sender = None  # thread holding _send_lock (re-entrancy guard)
        self._prewarmed = False
        # compile-telemetry cause hints for the NEXT compiling dispatch,
        # keyed per program mode (deliver bool): a full-width rebuild
        # invalidates BOTH programs, and each must attribute its own
        # rebuild compile (tail-variant hints are computed per call)
        self._cause_hints: dict = {}
        # batch-axis sharded execution (parallel/shard.py): armed by the
        # app's ShardRuntime ONLY when every endpoint is provably stateless
        # — micro-batches round-robin across devices, outputs merged back
        # in batch order. None = one attribute check per send.
        self.shard_router = None
        # lineage (observability/lineage.py): True when any endpoint has a
        # recorder armed — the chunk program then returns stacked `__lin.*`
        # lanes consumed per micro-batch; False = one check per chunk
        self._lin_any = any(
            getattr(ep.qr, "lineage", None) is not None
            for ep in self.endpoints
        )
        # sharded sends park observations here keyed by global batch index
        # so the recorder replays them in original batch order
        self._lin_pending = None
        ps = getattr(junction, "pipeline_stats", None)
        if ps is not None:
            ps.depth = self.pipeline_depth if self.pipeline_enabled else 0

    def describe_state(self) -> dict:
        """Introspection: chunking, pipeline depth/occupancy, slots in
        flight (see observability/introspect.py)."""
        d: dict = {
            "chunk_batches": self.K,
            "enabled": not self._disabled,
            "pipeline_enabled": self.pipeline_enabled,
            "depth": self.pipeline_depth if self.pipeline_enabled else 0,
            "component": self.component,
        }
        gr = self.group_report()
        if gr is not None:
            d["fusedgroup"] = gr
        if self.shard_router is not None:
            d["shard"] = self.shard_router.describe_state()
        ps = getattr(self.junction, "pipeline_stats", None)
        if ps is not None:
            d["occupancy"] = round(ps.occupancy(), 3)
        pl = self.pipeline
        if pl is not None:
            d.update(pl.describe_state())
        if self._narrow is not None:
            # per-column wire-encoding choices + encoded-vs-logical
            # bytes/event (core/wire.py), surfaced in /status.json,
            # explain(), and /profile
            from siddhi_tpu.core.wire import wire_report

            d["wire"] = wire_report(
                self.junction.schema, getattr(self, "_keep", None),
                self._narrow, self.wire_spec,
                capacity=self.junction.batch_size,
            )
        return d

    def force_full_width(self) -> None:
        """Pin the wire full-width permanently, discarding any chosen
        encodings (bench's enc-vs-raw A/B and tests; the same state a
        runtime misfit fallback lands in). The next send rebuilds the
        programs against the wide codec; call between sends only."""
        with self._lock:
            self._narrow = {}
            self._fused = None
            self._fused_deliver = None

    def group_report(self) -> Optional[dict]:
        """Achieved-vs-predicted dispatch reduction for a plan-driven fused
        group (None for the legacy whole-junction engine): chunk/batch/event
        counters, dispatches-per-chunk before/after, shared-ring refcounts.
        Surfaced through describe_state(), runtime.explain(), and /profile."""
        if self.plan_group is None:
            return None
        n = len(self.endpoints)
        rep: dict = {
            "component": self.component,
            "queries": list(self.plan_group.get("queries", ())),
            "chunks": self.chunks_dispatched,
            "batches": self.batches_fused,
            "events": self.events_fused,
            "dispatches_per_chunk_before": self.plan_group.get(
                "dispatches_per_chunk_before", n * self.K
            ),
            "dispatches_per_chunk_after": 1,
            "predicted_dispatch_reduction": self.plan_group.get(
                "est_dispatch_reduction"
            ),
        }
        if self.batches_fused:
            # per-batch equivalence: every fused micro-batch would have cost
            # one dispatch per group member on the unfused path
            rep["achieved_dispatch_reduction"] = round(
                1.0 - self.chunks_dispatched / (self.batches_fused * n), 4
            )
        if self.residual:
            rep["residual"] = [name for _fn, name in self.residual]
        if self.share_sets:
            rep["shared_state"] = [
                {
                    "queries": [
                        getattr(self.endpoints[i].qr, "query_id", i)
                        for i in idxs
                    ],
                    "refcount": len(idxs),
                }
                for idxs in self.share_sets
            ]
        return rep

    def wire_params(self):
        """(capacity, keep, narrow) — the exact wire codec the built fused
        program decodes; tools/bench must encode with the same triple."""
        return self.junction.batch_size, self._keep, (self._narrow or {})

    def staged_codec(self, ts_sample, cols_sample):
        """Bench/tool entry: sample the narrow wire (if unchosen), build the
        non-delivery fused program, and return (encode, wire_bytes) matching
        the program exactly — the one place the staging handshake lives."""
        with self._lock:
            if self._narrow is None:
                from siddhi_tpu.core.wire import choose_encodings

                self._narrow = choose_encodings(
                    self.junction.schema, self._compute_keep(),
                    self.wire_spec, self.wire_enabled,
                    ts_sample, cols_sample,
                )
            if self._fused is None:
                self._build()
            encode, _d, nb = self.junction.schema.wire_codec(
                *self.wire_params()
            )
        return encode, nb

    # ---- eligibility (cheap dynamic checks, every send) ------------------

    def eligible(self) -> bool:
        j = self.junction
        if j.is_async or j.stream_callbacks:
            return False
        if getattr(self.app, "_debugger", None) is not None:
            return False
        if len(j.subscribers) != len(self.endpoints) + len(self.residual):
            return False  # an uncovered subscriber is attached
        for ep in self.endpoints:
            qr = ep.qr
            if getattr(qr, "rate_limiter", None) is not None:
                return False
            # query callbacks are OK: the deliver-mode program packs outputs
            # device-side and drains them once per chunk (see _build_deliver)
            if _needs_scheduler(qr) or getattr(qr, "host_next_timer", None):
                return False
            tj = getattr(qr, "_insert_target_junction", None)
            if tj is not None and (
                tj.subscribers or tj.stream_callbacks
                or tj.on_publish_stats is not None
            ):
                return False
        return True

    def _delivery_set(self) -> frozenset:
        """Indices of endpoints whose outputs must be packed/drained."""
        return frozenset(
            i
            for i, ep in enumerate(self.endpoints)
            if getattr(ep.qr, "query_callbacks", None)
        )

    # ---- device program --------------------------------------------------

    def _compute_keep(self) -> frozenset | None:
        """Projected wire: ship only attributes some subscriber reads."""
        schema = self.junction.schema
        used: set | None = set()
        for ep in self.endpoints:
            ua = getattr(ep.qr, "used_attrs", None)
            if ua is None:
                used = None  # unknown/select * — keep everything
                break
            used |= ua
        self._keep = (
            None if used is None
            else frozenset(n for n in schema.attr_names if n in used)
        )
        return self._keep

    def _build(self, deliver_set: Optional[frozenset] = None):
        deliver = deliver_set is not None
        B = self.junction.batch_size
        schema = self.junction.schema
        self._compute_keep()
        _encode, decode, self._wire_bytes = schema.wire_codec(
            B, self._keep, self._narrow or {}
        )
        # roofline numerators: encoded bytes ship over the link; logical
        # bytes are what the full-width packed wire would have carried
        # (int64 ts + every column at physical width) — the live
        # logical-vs-encoded gauges divide both by h2d_events
        from siddhi_tpu.core.wire import logical_row_bytes

        self._logical_row_bytes = logical_row_bytes(schema.attrs)
        impls = [ep.impl_factory() for ep in self.endpoints]
        impls_want = [ep.qr.output_events for ep in self.endpoints]
        # deliver lanes ship only the out-schema columns: a lineage-armed
        # group-by step carries a __group_key__ col beside its outputs,
        # which the host deliver layout must never see
        out_names = [
            frozenset(ep.qr.out_schema.attr_names) for ep in self.endpoints
        ]
        share_of = dict(self._share_of)
        share_leader = dict(self._share_leader)
        has_share = bool(self.share_sets)

        def fused(states_all, tstates, wire, counts, bases, now):
            # with share sets, arg0 = (per-endpoint states with shared-member
            # chains STRIPPED, one canonical chain per set): the duplicate
            # ring is carried (and donated) exactly once, and every member's
            # window update reads the same buffers — XLA CSE collapses the
            # identical update computations into one
            if has_share:
                states, shared0 = states_all
            else:
                states, shared0 = states_all, ()

            def body(carry, xs):
                (sts, shr), tst = carry
                batch = decode(xs[0], xs[1], xs[2])
                new_states = []
                new_shr = list(shr)
                auxes = []
                lins = []
                outs = []
                for ei, (impl, st) in enumerate(zip(impls, sts)):
                    g = share_of.get(ei)
                    if g is not None:
                        # every member consumes the PREVIOUS iteration's
                        # canonical chain — exactly what its own chain would
                        # hold, by the share-set identity invariant
                        st = dict(st)
                        st["chain"] = shr[g]
                    st2, tst, out, aux = impl(st, tst, batch, now)
                    if g is not None:
                        st2 = dict(st2)
                        ch = st2.pop("chain")
                        if ei == share_leader[g]:
                            new_shr[g] = ch
                    new_states.append(st2)
                    auxes.append(
                        tuple(
                            jnp.asarray(v).astype(bool).any()
                            for k, v in sorted(aux.items())
                            if k != "next_timer"
                            and not k.startswith("__lin")
                        )
                    )
                    # lineage lanes (observability/lineage.py) bypass the
                    # boolean aux reduction: the scan STACKS them across
                    # the K micro-batches for the host recorder
                    lins.append({
                        k: v for k, v in aux.items()
                        if k.startswith("__lin")
                    })
                    if deliver and ei in deliver_set:
                        # ship the raw lanes + a deliverable-row mask; the
                        # post-scan pack compacts ALL K iterations with one
                        # cumsum + scatter (per-iteration argsort compaction
                        # measured ~2x slower). Kind-filter device-side when
                        # the query emits only one kind.
                        from siddhi_tpu.core.event import (
                            KIND_CURRENT as _KC,
                            KIND_EXPIRED as _KE,
                        )
                        from siddhi_tpu.query_api.execution import (
                            OutputEventsFor as _OEF,
                        )

                        want = impls_want[ei]
                        if want is _OEF.CURRENT:
                            dv = out.valid & (out.kind == _KC)
                        elif want is _OEF.EXPIRED:
                            dv = out.valid & (out.kind == _KE)
                        else:
                            dv = out.valid & (
                                (out.kind == _KC) | (out.kind == _KE)
                            )
                        lanes = {"ts": out.ts}
                        if want is _OEF.ALL:
                            lanes["kind"] = out.kind
                        lanes.update(
                            {
                                f"c.{n}": c
                                for n, c in out.cols.items()
                                if n in out_names[ei]
                            }
                        )
                        outs.append((lanes, dv))
                return (
                    ((tuple(new_states), tuple(new_shr)), tst),
                    (tuple(auxes), tuple(lins), tuple(outs)),
                )

            (
                ((states, shared), tstates),
                (aux_stack, lin_stack, out_stack),
            ) = lax.scan(
                body, ((states, shared0), tstates), (wire, counts, bases)
            )
            states_out = (states, shared) if has_share else states
            aux_red = tuple(
                tuple(v.any() for v in a) for a in aux_stack
            )
            if not deliver:
                return states_out, tstates, aux_red, lin_stack, ()
            # pack each endpoint's K compacted segments into ONE contiguous
            # ROW-MAJOR byte buffer [R, row_bytes]: the host drains exactly
            # the filled row prefix with a single contiguous slice transfer
            # (per-lane buffers would need one transfer each)
            from siddhi_tpu.ops.scatter import set_at

            packs = []
            for stacked, dv in out_stack:
                K = dv.shape[0]  # shape-driven: one traced fn serves any K
                cap = dv.shape[1]
                R = K * cap
                flat = dv.reshape(R)  # [K, cap] row-major = arrival order
                rank = jnp.cumsum(flat.astype(jnp.int32)) - flat.astype(
                    jnp.int32
                )
                dst = jnp.where(flat, rank, R)
                segs = []
                for name in sorted(stacked):
                    arr = stacked[name].reshape(R)
                    if arr.dtype == jnp.bool_:
                        arr = arr.astype(jnp.uint8)
                    packed = set_at(jnp.zeros((R,), arr.dtype), dst, arr)
                    u8 = jax.lax.bitcast_convert_type(packed, jnp.uint8)
                    if u8.ndim == 1:  # already byte-wide lanes
                        u8 = u8[:, None]
                    segs.append(u8)
                data_buf = jnp.concatenate(segs, axis=1)
                W = data_buf.shape[1]
                # header rows carry the per-iteration counts INSIDE the
                # buffer: the steady-state drain is then ONE d2h transfer
                # (each transfer pays a ~fixed relay round trip)
                cnt_u8 = jax.lax.bitcast_convert_type(
                    dv.sum(axis=1, dtype=jnp.int32), jnp.uint8
                ).reshape(-1)  # [4K]
                hdr_rows = -(-cnt_u8.shape[0] // W)
                hdr = jnp.zeros((hdr_rows * W,), jnp.uint8)
                hdr = hdr.at[: cnt_u8.shape[0]].set(cnt_u8).reshape(hdr_rows, W)
                packs.append(
                    {"buf": jnp.concatenate([hdr, data_buf], axis=0)}
                )
            return states_out, tstates, aux_red, lin_stack, tuple(packs)

        # donate the per-endpoint states (exclusively owned); tstates may
        # alias read-only findables shared with other runtimes — not donated
        prog = jax.jit(fused, donate_argnums=(0,))
        if deliver:
            self._fused_deliver = prog
            self._deliver_set = deliver_set
            self._deliver_idx = sorted(deliver_set)
            # host-side byte layout of each endpoint's drain buffer, in the
            # same sorted-lane order the device concatenated
            from siddhi_tpu.query_api.execution import OutputEventsFor

            self._deliver_layout = []
            for ep in self.endpoints:
                qr = ep.qr
                dtypes = {
                    f"c.{n}": np.dtype(a.dtype)
                    for n, a in qr.out_schema.empty_batch(1).cols.items()
                }
                dtypes["ts"] = np.dtype(np.int64)
                if qr.output_events is OutputEventsFor.ALL:
                    dtypes["kind"] = np.dtype(np.int8)
                layout = []
                off = 0
                for name in sorted(dtypes):
                    dt = dtypes[name]
                    layout.append((name, dt, off))
                    off += dt.itemsize
                self._deliver_layout.append((layout, off))
        else:
            self._fused = prog
        self._aux_keys = [self._probe_aux_keys(i) for i in range(len(impls))]

    # ---- host side -------------------------------------------------------

    def _chunk_K(self, remaining_batches: int) -> int:
        """Smallest K variant covering the remainder: full chunks use self.K;
        a short tail picks the smallest power-of-two variant that holds it, so
        chunk-granularity producers stay on the fused path without paying a
        full K-iteration scan of empty batches. jax.jit retraces per wire
        shape, so each variant compiles once and is cached — a workload whose
        tail sizes alternate pays each variant's one-time compile the first
        time that tail size appears mid-traffic (at most log2(K) compiles;
        SIDDHI_TPU_PREWARM_TAIL=1 pre-compiles the smallest variant at first
        engagement to take the worst of it off the traffic path, see
        _prewarm_tail)."""
        if remaining_batches >= self.K:
            return self.K
        k = 2
        while k < remaining_batches:
            k *= 2
        return min(k, self.K)

    def try_send(self, timestamps, cols, now: int) -> bool:
        """Attempt fused ingest of the whole call. Returns False to make the
        caller fall back to the per-batch path."""
        n = len(timestamps)
        B = self.junction.batch_size
        # engage for any call of at least two micro-batches: shorter tails
        # ride a smaller-K variant of the same program (see _chunk_K)
        if n < 2 * B or self._disabled or not self.eligible():
            return False
        dset = self._delivery_set()
        deliver = bool(dset)
        ts_arr = np.asarray(timestamps)
        if n and int(ts_arr.max()) - int(ts_arr.min()) >= (1 << 31):
            return False  # int32 ts-delta wire can't span >24 days per call
        with self._lock:
            if deliver and getattr(self, "_deliver_set", None) != dset:
                if self._fused_deliver is not None:
                    from siddhi_tpu.observability.profiler import (
                        CAUSE_DELIVER_SET,
                    )

                    self._cause_hints[True] = CAUSE_DELIVER_SET
                self._fused_deliver = None  # callback set changed: rebuild
            if (self._fused_deliver if deliver else self._fused) is None:
                try:
                    if self._narrow is None:
                        # wire-encoding decision at first engagement
                        # (core/wire.py): the static WireSpec's
                        # analyzer-chosen encoders (dict/delta/range-narrow/
                        # bitpack) overlaid on dtypes sampled from the first
                        # micro-batch; {} (full width) when disabled. Any
                        # later misfit rebuilds full-width (once).
                        from siddhi_tpu.core.wire import choose_encodings

                        self._narrow = choose_encodings(
                            self.junction.schema, self._compute_keep(),
                            self.wire_spec, self.wire_enabled,
                            ts_arr[:B],
                            {k: np.asarray(v)[:B] for k, v in cols.items()},
                        )
                    self._build(deliver_set=dset if deliver else None)
                except Exception:
                    import logging

                    logging.getLogger(__name__).warning(
                        "fused ingest disabled for stream '%s' (build failed)",
                        self.junction.schema.stream_id, exc_info=True,
                    )
                    self._disabled = True
                    return False
            # snapshot the (program, encode) PAIR under the lock: a misfit
            # rebuild in another thread swaps both _narrow and the programs,
            # and an unlocked read could pair a full-width encode with the
            # old narrow-decoding program (silent corruption)
            prog = self._fused_deliver if deliver else self._fused
            encode, _decode, _nb = self.junction.schema.wire_codec(
                B, self._keep, self._narrow or {}
            )

        if not self._prewarmed:
            self._prewarm_tail(prog, now)

        # flight recorder: the fused path never materializes an EventBatch
        # host-side, so record straight from the (host, physical) columns —
        # but only once a send path COMMITS (returns True): a False return
        # re-sends the same events through the per-batch path, whose
        # publish_batch would record them a second time
        def record_flight(ok: bool) -> bool:
            fl = self.junction.flight
            if ok and fl is not None:
                fl.record_columns(ts_arr, cols, n)
            bb = self.junction.blackbox
            if ok and bb is not None:
                # black-box ring: same once-per-commit contract
                bb.record_columns(ts_arr, cols, n)
            la = self.junction.lineage
            if ok and la is not None:
                # lineage stamp: the fused commit is this send's one
                # publish — same once-per-commit contract as the flight
                # ring (a False return re-sends per batch, whose
                # publish_batch stamps instead)
                la.record_columns(ts_arr, cols, n)
            if not ok:
                return False
            if self.residual:
                # fused chunks committed (group callbacks delivered at the
                # barrier above); now the blocked consumers get the same
                # events per batch, preserving their unfused semantics
                self._residual_dispatch(ts_arr, cols, n, now)
            return True

        # observability hooks: device-budget trackers on the junction plus
        # per-endpoint latency trackers (recording CHUNK dispatch wall time —
        # in fused mode the chunk is the unit of processing). All None/empty
        # when statistics are off: the loops below pay one truthiness check.
        ds = self.junction.device_stats
        tracked = [
            ep.latency_tracker
            for ep in self.endpoints
            if ep.latency_tracker is not None
        ]
        tr = self.junction.tracer
        stream_span = f"stream.{self.junction.schema.stream_id}"

        # batch-axis sharded execution (parallel/shard.py): round-robin the
        # call's micro-batches across devices and merge outputs in batch
        # order. None = not sharded; a None RESULT = the router declined
        # (too few batches / narrow-wire misfit) and the single-device
        # paths below own the call.
        if self.shard_router is not None:
            sent = self.shard_router.try_send(
                self, prog, encode, deliver, ts_arr, cols, n, B, now,
                ds, tracked, tr, stream_span,
            )
            if sent is not None:
                return record_flight(sent)

        if self.pipeline_enabled:
            pl = self._pipeline()
            # a query callback that re-enters send_columns from the drain
            # worker — or, in inline-drain mode, from the sending thread
            # itself — must not block on the pipeline it is draining
            if (
                not pl.is_drain_thread()
                and self._sender is not threading.current_thread()
            ):
                with self._send_lock:
                    self._sender = threading.current_thread()
                    try:
                        return record_flight(self._send_pipelined(
                            prog, encode, deliver, dset, ts_arr, cols, n, B,
                            now, ds, tracked, tr, stream_span, pl,
                        ))
                    finally:
                        self._sender = None
        return record_flight(self._send_serial(
            prog, encode, deliver, dset, ts_arr, cols, n, B, now,
            ds, tracked, tr, stream_span,
        ))

    def _pipeline(self):
        pl = self.pipeline
        if pl is None:
            from siddhi_tpu.core.pipeline import IngestPipeline

            pl = self.pipeline = IngestPipeline(
                self.junction, depth=self.pipeline_depth,
                drain_fn=self._drain,
            )
            pl.stats = getattr(self.junction, "pipeline_stats", None)
        return pl

    def close(self) -> None:
        """Stop the pipeline's drain worker (app shutdown). Serialized with
        senders so no in-flight send can enqueue behind the stop sentinel
        and strand its barrier."""
        with self._send_lock:
            pl = self.pipeline
            if pl is not None:
                pl.close()

    def _rebuild_full_width(self, deliver: bool, dset):
        """A value outgrew the sampled narrow wire: rebuild the fused program
        full-width (once, permanent). Program and encode are swapped under
        the same lock so no reader pairs a full-width encode with the old
        narrow-decoding program. Raises on rebuild failure (caller disables
        the fused path)."""
        with self._lock:
            self._narrow = {}
            self._fused = None
            self._fused_deliver = None
            self._build(deliver_set=dset if deliver else None)
            prog = self._fused_deliver if deliver else self._fused
            encode, _decode, _nb = self.junction.schema.wire_codec(
                self.junction.batch_size, self._keep, {}
            )
            from siddhi_tpu.observability.profiler import CAUSE_FULL_WIDTH

            # both programs were discarded: each mode's next compile is
            # rebuild-caused
            self._cause_hints[False] = CAUSE_FULL_WIDTH
            self._cause_hints[True] = CAUSE_FULL_WIDTH
        return prog, encode

    def _dispatch_chunk(
        self, prog, wire, counts, bases, now, ds, tracked, tr, stream_span,
        ps=None, wf=None, deliver=False, lin_ks=None,
    ):
        """One donated-state dispatch under the app lock: collect states,
        run the program, write back, publish stats, surface aux flags.
        Returns (packs, completion) — completion is one device output of
        the dispatch, whose readiness implies the program (and so its read
        of the wire buffer) finished; the pipelined path hands it to
        IngestPipeline.retire. On a dispatch failure owned by the
        junction's exception handler returns (None, None) and the caller
        skips to the next chunk, like per-batch send_columns would."""
        with self.app._process_lock:
            states = []
            for ep in self.endpoints:
                if ep.qr.state is None:
                    ep.qr.state = ep.qr._fresh(ep.init_state(now))
                states.append(ep.qr.state)
            arg0 = self._pack_arg0(states)
            tstates = {}
            ep_tids = []
            for ep in self.endpoints:
                ts_ep = ep.qr._collect_table_states()
                ep_tids.append(list(ts_ep))
                tstates.update(ts_ep)
            span = (
                tr.start_span(stream_span, int(counts.sum()))
                if tr is not None
                else None
            )
            ct = self.junction.compile_telemetry
            t0 = (
                time.perf_counter_ns()
                if (
                    ds is not None or tracked or ps is not None
                    or ct is not None or wf is not None
                )
                else 0
            )
            try:
                # fault-injection site `device_dispatch` (testing/faults.py):
                # inside the try so an injected failure rides the exact
                # donated-state reset + junction-failure-policy path a real
                # chunk-program explosion takes
                if _faults.ACTIVE is not None:
                    _faults.ACTIVE.check("device_dispatch", self.component)
                new_all, tstates, aux_red, lin_stack, packs = prog(
                    arg0, tstates, wire,
                    counts, bases, np.int64(now),
                )
                if t0:
                    dt = time.perf_counter_ns() - t0
                    for lt in tracked:
                        lt.record_ns(dt)
                    if ds is not None:
                        ds.step.record_ns(dt)
                        ds.h2d_bytes.add(int(wire.nbytes))
                        ds.h2d_chunks.add(1)
                        # live roofline numerator/denominator pair: the
                        # always-on wire bytes/event gauge rides these
                        n_ev = int(counts.sum())
                        ds.h2d_events.add(n_ev)
                        # logical-vs-encoded split (core/wire.py): what the
                        # full-width wire would have shipped for the same
                        # events, so the encoded gauge has a denominator
                        ds.h2d_logical.add(
                            n_ev * self._logical_row_bytes
                        )
                    if ps is not None:
                        ps.dispatch.record_ns(dt)
                    if wf is not None:
                        wf.stage("dispatch", dt)
                    if ct is not None:
                        # fused compile telemetry: the chunk program retraces
                        # per (K, wire width); rebuild paths leave a cause
                        # hint, short tails are tail-variant compiles
                        K = int(counts.shape[0])
                        hint = self._cause_hints.pop(deliver, None)
                        if hint is None and K < self.K:
                            from siddhi_tpu.observability.profiler import (
                                CAUSE_TAIL_K,
                            )

                            hint = CAUSE_TAIL_K
                        ct.observe(
                            self.component + ("_deliver" if deliver else ""),
                            prog, (K, int(wire.shape[1])), dt,
                            cause_hint=hint,
                        )
            except Exception as e:
                # the call donated the state buffers: they are gone either
                # way, so reset to fresh state (lazily re-initialized on
                # the next receive) instead of leaving every later send
                # crashing on deleted arrays; then honor the junction's
                # failure policy like the per-batch path does (which
                # drops at most the failing batch and keeps going)
                for ep in self.endpoints:
                    ep.qr.state = None
                self._aliased = False
                handler = self.junction.exception_handler
                if handler is None:
                    raise
                handler(e)
                return None, None
            finally:
                if span is not None:
                    tr.end_span(span)
            self._writeback_states(new_all)
            for ep, tids in zip(self.endpoints, ep_tids):
                ep.qr._writeback_table_states(
                    {tid: tstates[tid] for tid in tids}
                )
        self.chunks_dispatched += 1
        self.batches_fused += int(counts.shape[0])
        self.events_fused += int(counts.sum())
        if self.junction.on_publish_stats is not None:
            self.junction.on_publish_stats(int(counts.sum()))
        for i, ep in enumerate(self.endpoints):
            flags = dict(zip(self._aux_keys[i], aux_red[i]))
            if flags:
                ep.qr._warn_aux(flags)
        if self._lin_any:
            # provenance readback (one d2h when lineage is on): feed each
            # armed endpoint's recorder per micro-batch, in chunk order —
            # or park with the global batch index when the shard router
            # dispatches chunks round-robin (see _lin_begin_send)
            self._lin_observe_chunk(lin_stack, counts, now, lin_ks)
        # completion: ONLY leaves that are never donated to a later dispatch
        # (aux flags, output packs, table states). The query states are
        # donated at the NEXT dispatch's submit — which deletes the array
        # long before THIS dispatch completes, so gating a wire slot on one
        # would free the buffer while the program still reads it. With no
        # such leaf the caller gets None and retire() abandons the aliased
        # buffer instead of reusing it.
        leaves = jax.tree_util.tree_leaves((aux_red, packs, tstates))
        return packs, (leaves[0] if leaves else None)

    # ---- lineage observation (observability/lineage.py) ------------------

    def _lin_observe_chunk(self, lin_stack, counts, now, lin_ks=None) -> None:
        """Feed each armed endpoint's recorder the chunk's stacked `__lin.*`
        lanes, one micro-batch at a time. With `lin_ks` (the sharded
        router's global batch indices for this chunk) observations are
        parked for the in-order replay at _lin_end_send()."""
        import numpy as _np

        K = int(counts.shape[0])
        for i, ep in enumerate(self.endpoints):
            lin = getattr(ep.qr, "lineage", None)
            stacks = lin_stack[i] if i < len(lin_stack) else None
            if lin is None or not stacks:
                continue
            host = {k: _np.asarray(v) for k, v in stacks.items()}
            tag = getattr(ep, "lineage_tag", None)
            for k in range(K):
                if int(counts[k]) == 0:
                    continue  # padding iteration: no valid rows
                lanes = {kk: v[k] for kk, v in host.items()}
                if lin_ks is not None and self._lin_pending is not None:
                    self._lin_pending.append(
                        (int(lin_ks[k]), i, lin, lanes, now, tag)
                    )
                else:
                    self._lin_observe_one(lin, lanes, now, tag)

    @staticmethod
    def _lin_observe_one(lin, lanes, now, tag) -> None:
        try:
            lin.observe(lanes, now, tag)
        except Exception:  # provenance must never break dispatch
            import logging

            logging.getLogger(__name__).debug(
                "fused lineage observe failed", exc_info=True
            )

    def _lin_begin_send(self) -> None:
        if self._lin_any:
            self._lin_pending = []

    def _lin_end_send(self) -> None:
        pend, self._lin_pending = self._lin_pending, None
        if pend:
            # original batch order, then endpoint order — exactly the
            # single-device chunk loop's observation order
            for _k, _i, lin, lanes, now, tag in sorted(
                pend, key=lambda x: (x[0], x[1])
            ):
                self._lin_observe_one(lin, lanes, now, tag)

    # ---- cross-query state sharing (plan share sets) ---------------------

    def _pack_arg0(self, full_states):
        """Program arg0 from the per-endpoint full states: with share sets,
        shared members' chains are stripped and each set's canonical chain
        (the leader's) rides once — so the shared ring's buffers are donated
        exactly once per dispatch."""
        if not self.share_sets:
            return tuple(full_states)
        stripped = tuple(
            {k: v for k, v in st.items() if k != "chain"}
            if i in self._share_of else st
            for i, st in enumerate(full_states)
        )
        shared = tuple(
            full_states[idxs[0]]["chain"] for idxs in self.share_sets
        )
        return (stripped, shared)

    def _writeback_states(self, new_all) -> None:
        """Write the program's output states back onto the runtimes; shared
        members get the canonical chain re-attached (ALIASED across the set
        — one ring serves every member until _maybe_unshare splits it)."""
        if not self.share_sets:
            for ep, st in zip(self.endpoints, new_all):
                ep.qr.state = st
            return
        new_states, new_shared = new_all
        for i, (ep, st) in enumerate(zip(self.endpoints, new_states)):
            g = self._share_of.get(i)
            if g is not None:
                st = {**st, "chain": new_shared[g]}
            ep.qr.state = st
        self._aliased = True

    def _maybe_unshare(self) -> None:
        """Split aliased chain states before any per-batch dispatch can
        donate them: each per-query jitted step donates its own state, and
        two runtimes donating the SAME ring buffers would use-after-free.
        Followers get a device copy; by the share-set identity invariant the
        values stay equal, so a later fused send re-shares losslessly.

        Called from each member's QueryRuntime.receive (the `_unshare_guard`
        hook) INSIDE the app process lock — the lock the fused dispatch's
        writeback aliases chains under — so the check cannot race an
        in-flight fused send: either the writeback happened-before (the
        guard splits here) or happens-after (our per-batch step ran on
        unaliased state). Only share-set members pay the call; the lock is
        an RLock the receive path already holds."""
        with self.app._process_lock:
            if not self._aliased:
                return
            self._aliased = False
            for idxs in self.share_sets:
                for i in idxs[1:]:
                    qr = self.endpoints[i].qr
                    st = qr.state
                    if st is None or "chain" not in st:
                        continue
                    st = dict(st)
                    st["chain"] = jax.tree_util.tree_map(
                        lambda x: jnp.array(x, copy=True)
                        if hasattr(x, "dtype") else x,
                        st["chain"],
                    )
                    qr.state = st

    # ---- residual per-batch dispatch (blocked queries) -------------------

    def _residual_dispatch(self, ts_arr, cols, n: int, now: int) -> None:
        """Re-dispatch the committed send per micro-batch to the junction
        subscribers OUTSIDE the fused group (the plan's SA124-blocked
        queries, aggregations): their per-batch semantics — rate limiters,
        schedulers, observed insert targets — are preserved exactly, while
        the group still collapsed its own n*K dispatches into one per chunk.
        Events were already flight-recorded and throughput-counted by the
        fused commit; dispatch_subset skips both."""
        j = self.junction
        B = j.batch_size
        encode, decode = j.schema.packed_codec(B)
        for ofs in range(0, n, B):
            end = min(ofs + B, n)
            m = end - ofs
            buf = encode(
                ts_arr[ofs:end],
                {k: v[ofs:end] for k, v in cols.items()},
                m,
            )
            j.dispatch_subset(decode(buf, np.int32(m)), now, self.residual)

    def _send_serial(
        self, prog, encode, deliver, dset, ts_arr, cols, n, B, now,
        ds, tracked, tr, stream_span,
    ) -> bool:
        """The fully serial chunk loop (@pipeline(disable='true') or a
        drain-worker re-entrant send): encode, dispatch, and drain the
        previous chunk's outputs on the calling thread, in order."""
        prof = self.junction.profiler
        pending_drain = None  # previous chunk's packs, drained one chunk late
        c_off = 0
        while c_off < n:
            K = self._chunk_K(-(-(n - c_off) // B))
            c_end = min(c_off + K * B, n)
            wf = (
                prof.begin(self.junction.schema.stream_id, c_end - c_off)
                if prof is not None
                else None
            )
            t_enc = time.perf_counter_ns() if wf is not None else 0
            try:
                wire, counts, bases = self._encode_chunk(
                    encode, ts_arr, cols, c_off, c_end, B, K
                )
            except WireNarrowMisfit:
                try:
                    prog, encode = self._rebuild_full_width(deliver, dset)
                except Exception as e:
                    import logging

                    logging.getLogger(__name__).warning(
                        "fused ingest disabled for stream '%s' (full-width "
                        "rebuild failed)", self.junction.schema.stream_id,
                        exc_info=True,
                    )
                    self._disabled = True
                    if c_off == 0:
                        return False  # nothing ingested: per-batch fallback
                    # earlier chunks are committed: deliver their parked
                    # outputs, then honor the junction's failure policy for
                    # the remainder (like a failing batch)
                    if pending_drain is not None:
                        self._drain_guarded(*pending_drain)
                    handler = self.junction.exception_handler
                    if handler is None:
                        raise
                    handler(e)
                    return True
                wire, counts, bases = self._encode_chunk(
                    encode, ts_arr, cols, c_off, c_end, B, K
                )
            if wf is not None:
                wf.stage("encode", time.perf_counter_ns() - t_enc)

            packs, _completion = self._dispatch_chunk(
                prog, wire, counts, bases, now, ds, tracked, tr, stream_span,
                wf=wf, deliver=deliver,
            )
            if packs is not None and deliver:
                # drain the PREVIOUS chunk now that this chunk's device work
                # is launched: the host decode overlaps device compute, and
                # callbacks still fire in order before send_columns returns
                if pending_drain is not None:
                    self._drain_guarded(*pending_drain)
                if wf is not None:
                    wf.t_mark = time.perf_counter_ns()
                pending_drain = (packs, K, wf)
            else:
                if prof is not None:
                    prof.end(wf)
            c_off = c_end
        if pending_drain is not None:
            self._drain_guarded(*pending_drain)
        return True

    def _drain_guarded(self, packs, K: int, wf=None) -> None:
        """Drain with the junction's failure machinery owning callback
        errors (same contract on every ingest path — per-batch dispatch,
        @async workers, pipelined drain): guarded junctions route the
        failure, unguarded ones re-raise to the sender."""
        try:
            self._drain(packs, K, wf)
        except Exception as e:
            j = self.junction
            if j.exception_handler is None and j.fault_policy is None:
                raise
            j._on_worker_error(e, "fused drain")

    def _send_pipelined(
        self, prog, encode, deliver, dset, ts_arr, cols, n, B, now,
        ds, tracked, tr, stream_span, pl,
    ) -> bool:
        """The double-buffered chunk loop (core/pipeline.py): chunk N+1 is
        encoded into a pooled buffer and device_put while chunk N's dispatch
        is in flight; deliver-mode drains run on the pipeline's bounded
        worker in chunk order. Barriers on the drain before returning, so
        callers observe the exact callback ordering of the serial path."""
        ps = pl.stats
        wall0 = time.perf_counter_ns() if ps is not None else 0
        err = None
        dispatched = False
        try:
            c_off = 0
            staged, c_off, prog, encode = self._stage_chunk(
                pl, prog, encode, deliver, dset, ts_arr, cols,
                c_off, n, B, ps,
            )
            while staged is not None:
                dev_wire, counts, bases, K, slot, wf = staged
                staged = None
                packs, completion = self._dispatch_chunk(
                    prog, dev_wire, counts, bases, now, ds, tracked, tr,
                    stream_span, ps, wf=wf, deliver=deliver,
                )
                pl.retire(slot, completion)
                dispatched = True
                if deliver and packs is not None:
                    # hand the packs to the drain worker BEFORE staging the
                    # next chunk: nothing downstream can lose them, and the
                    # worker's readback+decode overlaps the encode below
                    if wf is not None:
                        wf.t_mark = time.perf_counter_ns()
                    pl.submit(packs, K, wf)
                elif wf is not None:
                    prof = self.junction.profiler
                    if prof is not None:
                        prof.end(wf)
                if deliver and pl.pending_error():
                    # an unguarded delivery failure is waiting at the
                    # barrier: stop ingesting further chunks, like the
                    # serial path's drain raising mid-loop
                    break
                if c_off < n:
                    # overlap: this encode + h2d ride alongside the
                    # in-flight dispatch above
                    staged, c_off, prog, encode = self._stage_chunk(
                        pl, prog, encode, deliver, dset, ts_arr, cols,
                        c_off, n, B, ps,
                    )
        except _RebuildFailed as rf:
            err = rf
        except BaseException as e:
            err = e
        # always flush delivery before returning or raising: callbacks fire
        # in chunk order and complete before send_columns returns
        try:
            pl.barrier()
        except Exception as be:
            if err is None:
                err = be
        if wall0:
            ps.add_wall(time.perf_counter_ns() - wall0)
        if isinstance(err, _RebuildFailed):
            if not dispatched:
                return False  # nothing ingested: per-batch fallback
            handler = self.junction.exception_handler
            if handler is None:
                raise err.cause
            handler(err.cause)
            return True
        if err is not None:
            raise err
        return True

    def _stage_chunk(
        self, pl, prog, encode, deliver, dset, ts_arr, cols, c_off, n, B, ps
    ):
        """Encode the next chunk into a pooled wire buffer and start its
        async h2d transfer. Returns ((dev_wire, counts, bases, K, slot, wf),
        next_off, prog, encode) — prog/encode may have been swapped by a
        full-width rebuild on a narrow-wire misfit; the caller must
        pl.retire(slot, ...) once the chunk's dispatch is submitted."""
        K = self._chunk_K(-(-(n - c_off) // B))
        c_end = min(c_off + K * B, n)
        prof = self.junction.profiler
        wf = (
            prof.begin(self.junction.schema.stream_id, c_end - c_off)
            if prof is not None
            else None
        )
        t0 = time.perf_counter_ns() if (ps is not None or wf is not None) else 0
        try:
            slot = pl.acquire(K, self._wire_bytes)
            wire, counts, bases = self._encode_chunk(
                encode, ts_arr, cols, c_off, c_end, B, K, out=slot.buf
            )
        except WireNarrowMisfit:
            # drain everything first: the pending packs were produced by the
            # narrow program and must decode under the OLD deliver layout
            pl.barrier()
            try:
                prog, encode = self._rebuild_full_width(deliver, dset)
            except Exception as e:
                import logging

                logging.getLogger(__name__).warning(
                    "fused ingest disabled for stream '%s' (full-width "
                    "rebuild failed)", self.junction.schema.stream_id,
                    exc_info=True,
                )
                self._disabled = True
                raise _RebuildFailed(e) from e
            slot = pl.acquire(K, self._wire_bytes)
            wire, counts, bases = self._encode_chunk(
                encode, ts_arr, cols, c_off, c_end, B, K, out=slot.buf
            )
        if t0:
            dt = time.perf_counter_ns() - t0
            if ps is not None:
                ps.encode.record_ns(dt)
            if wf is not None:
                wf.stage("encode", dt)
            t0 = time.perf_counter_ns()
        dev_wire = pl.ship(slot)
        if t0:
            dt = time.perf_counter_ns() - t0
            if ps is not None:
                ps.h2d.record_ns(dt)
            if wf is not None:
                wf.stage("h2d", dt)
        return (dev_wire, counts, bases, K, slot, wf), c_end, prog, encode

    def _prewarm_tail(self, prog, now: int) -> None:
        """Opt-in (SIDDHI_TPU_PREWARM_TAIL=1): compile the smallest tail
        variant (K=2) at first engagement — on throwaway donated states and
        an all-empty wire — so alternating tail sizes don't pay a cold
        device compile mid-traffic (see _chunk_K). Off by default: it adds
        one compile per engaged junction whether or not tails ever occur."""
        import os

        self._prewarmed = True
        if self.K <= 2 or os.environ.get("SIDDHI_TPU_PREWARM_TAIL") != "1":
            return
        try:
            wire = np.zeros((2, self._wire_bytes), dtype=np.uint8)
            counts = np.zeros((2,), dtype=np.int32)
            bases = np.zeros((2,), dtype=np.int64)
            with self.app._process_lock:
                states = tuple(
                    ep.qr._fresh(ep.init_state(now)) for ep in self.endpoints
                )
                tstates = {}
                for ep in self.endpoints:
                    tstates.update(ep.qr._collect_table_states())
                # zero counts: every lane is invalid, no state is observable;
                # the throwaway states are donated, the table states are not
                prog(
                    self._pack_arg0(list(states)), tstates, wire, counts,
                    bases, np.int64(now),
                )
        except Exception:
            import logging

            logging.getLogger(__name__).debug(
                "tail-variant prewarm failed for stream '%s'",
                self.junction.schema.stream_id, exc_info=True,
            )

    def _encode_chunk(self, encode, ts_arr, cols, c_off, c_end, B, K, out=None):
        """Encode one K-batch chunk into the [K, bytes] wire stack; with
        `out` (a pooled pipeline buffer) the rows are written in place
        instead of allocating a fresh stack."""
        bufs = [] if out is None else None
        counts = np.zeros((K,), dtype=np.int32)
        bases = np.zeros((K,), dtype=np.int64)
        for k in range(K):
            lo = c_off + k * B
            hi = min(lo + B, c_end)
            m = max(hi - lo, 0)
            counts[k] = m
            if m > 0:
                buf, base = encode(
                    ts_arr[lo:hi],
                    {kk: v[lo:hi] for kk, v in cols.items()},
                    m,
                )
                bases[k] = base
                if out is None:
                    bufs.append(buf)
                else:
                    out[k, :] = buf
            elif out is None:
                bufs.append(np.zeros_like(bufs[0]))
            else:
                out[k, :] = 0
        if out is not None:
            return out, counts, bases  # [K, bytes]
        return np.stack(bufs), counts, bases  # [K, bytes]

    def _drain(self, packs, K: int, wf=None) -> None:
        """Deliver one chunk's packed outputs to query callbacks: one counts
        readback + one sliced transfer per endpoint-with-callbacks, then a
        vectorized host decode, preserving per-micro-batch callback grouping
        (reference: QueryCallback.receive per chunk,
        query/output/callback/QueryCallback.java:52-105). `K` is the chunk's
        batch count (variable: short tails ride smaller-K programs).

        With a waterfall `wf` (observability/profiler.py), the drain
        attributes its spans: `queue` (dispatch-submit to drain-start),
        `device` (the FIRST blocking readback, dominated by waiting for the
        program), `readback` (top-up transfers), `deliver` (decode +
        callback wall), then closes the chunk's record."""
        import jax

        if not hasattr(self, "_drain_guess"):
            self._drain_guess = {}
        ds = self.junction.device_stats
        wf_get_ns = 0  # device+readback spans, excluded from 'deliver'
        first_get = True
        t_drain0 = 0
        if wf is not None:
            t_drain0 = time.perf_counter_ns()
            if wf.t_mark:
                wf.stage("queue", t_drain0 - wf.t_mark)
                wf.t_mark = 0
        # packs align with the endpoints the program was built to deliver
        for i, pack in zip(self._deliver_idx, packs):
            qr = self.endpoints[i].qr
            if not getattr(qr, "query_callbacks", None):
                continue
            layout, row_bytes = self._deliver_layout[i]
            hdr_rows = -(-4 * K // row_bytes)
            R = pack["buf"].shape[0] - hdr_rows

            def bucket(x: int) -> int:
                return min(R, 1 << max(0, int(x - 1).bit_length()))

            # ONE round trip in the steady state: the buffer's header rows
            # carry the per-iteration counts, and the prefix is sized from
            # the previous chunk's total; top up only when the guess
            # undershoots (workload rates are stable)
            guess = bucket(self._drain_guess.get(i, R))
            # ascontiguousarray: this backend's device_get can hand back a
            # strided view of the device-layout buffer for some slice sizes,
            # and the .view(dtype) reinterprets below require dense bytes
            t0 = (
                time.perf_counter_ns()
                if (ds is not None or wf is not None)
                else 0
            )
            head = np.ascontiguousarray(
                jax.device_get(pack["buf"][: hdr_rows + guess])
            )
            if t0:
                dt = time.perf_counter_ns() - t0
                if ds is not None:
                    ds.sync_stall.record_ns(dt)
                if wf is not None:
                    # the first blocking readback waits for the program:
                    # that's the chunk's device span; later ones are pure
                    # readback
                    wf.stage("device" if first_get else "readback", dt)
                    first_get = False
                    wf_get_ns += dt
            cnts = head[:hdr_rows].reshape(-1)[: 4 * K].view(np.int32)
            total = int(cnts.sum())
            self._drain_guess[i] = max(total, 1)
            if total == 0:
                continue
            L = bucket(total)
            if L <= guess:
                host = head[hdr_rows:]
            else:
                t0 = (
                    time.perf_counter_ns()
                    if (ds is not None or wf is not None)
                    else 0
                )
                tail = np.ascontiguousarray(
                    jax.device_get(
                        pack["buf"][hdr_rows + guess : hdr_rows + L]
                    )
                )
                if t0:
                    dt = time.perf_counter_ns() - t0
                    if ds is not None:
                        ds.sync_stall.record_ns(dt)
                    if wf is not None:
                        wf.stage("readback", dt)
                        first_get = False
                        wf_get_ns += dt
                host = np.concatenate([head[hdr_rows:], tail])
            self.deliver_endpoint(i, host, cnts, total)
        if wf is not None:
            # deliver = the drain wall minus the blocking readbacks
            wf.stage(
                "deliver",
                time.perf_counter_ns() - t_drain0 - wf_get_ns,
            )
            prof = self.junction.profiler
            if prof is not None:
                prof.end(wf)

    def deliver_endpoint(self, i: int, host, cnts, total: int) -> None:
        """Decode endpoint `i`'s packed output rows and fire its callbacks
        per micro-batch segment. `host` is the header-stripped byte buffer
        (rows at the front, `row_bytes` wide per `_deliver_layout[i]`),
        `cnts` the deliverable-row count per micro-batch IN DELIVERY ORDER,
        `total` their sum. Shared by `_drain` (one chunk's buffer) and the
        batch shard router's merged drain (segments interleaved back into
        global batch order, parallel/shard.py) — one delivery code path, so
        callback grouping/ordering semantics cannot drift between them."""
        from siddhi_tpu.core.event import (
            KIND_CURRENT,
            KIND_EXPIRED,
            rows_from_arrays,
        )
        from siddhi_tpu.query_api.execution import OutputEventsFor

        qr = self.endpoints[i].qr
        sm = getattr(self.app, "statistics_manager", None)
        if sm is not None and total:
            # fused insert targets are dead-end junctions (eligible()
            # excludes subscribed targets), so the per-publish throughput
            # hook never fires for them; meter delivered rows here so the
            # calibration ledger can pair predicted selectivity against an
            # actual out-rate on the fused path
            sm.throughput_tracker(
                f"stream.{qr.out_schema.stream_id}"
            ).add(total)
        layout, _row_bytes = self._deliver_layout[i]
        lanes = {}
        for name, dt, off in layout:
            lanes[name] = np.ascontiguousarray(
                host[:total, off : off + dt.itemsize]
            ).view(dt)[:, 0]
        want = qr.output_events
        cols = {n: lanes[f"c.{n}"] for n in qr.out_schema.attr_names}
        raw = getattr(qr, "raw_query_callbacks", None)
        if want is not OutputEventsFor.ALL and raw is not None and len(
            raw
        ) == len(qr.query_callbacks):
            # single-kind fast path: decode straight to Event lists and
            # invoke the USER callbacks (skips the triple intermediate)
            from siddhi_tpu.core.event import events_from_arrays

            events = events_from_arrays(
                qr.out_schema, lanes["ts"], cols, total, qr._interner
            )
            expired = want is OutputEventsFor.EXPIRED
            off = 0
            for k in range(len(cnts)):
                c = int(cnts[k])
                if c == 0:
                    continue
                seg = events[off : off + c]
                off += c
                ts = seg[-1][0]
                for cb in raw:
                    if expired:
                        cb(ts, None, seg)
                    else:
                        cb(ts, seg, None)
            return
        kind = (
            lanes["kind"]
            if want is OutputEventsFor.ALL
            else int(
                KIND_CURRENT
                if want is not OutputEventsFor.EXPIRED
                else KIND_EXPIRED
            )
        )
        rows = rows_from_arrays(
            qr.out_schema, lanes["ts"], kind, cols, total, qr._interner
        )
        split = want is OutputEventsFor.ALL
        off = 0
        for k in range(len(cnts)):
            c = int(cnts[k])
            if c == 0:
                continue
            seg = rows[off : off + c]
            off += c
            if split:
                ins = [e for e in seg if e[1] == KIND_CURRENT]
                removed = [e for e in seg if e[1] == KIND_EXPIRED]
            elif want is OutputEventsFor.EXPIRED:
                ins, removed = [], seg
            else:
                ins, removed = seg, []
            if ins or removed:
                ts = seg[-1][0]
                for cb in qr.query_callbacks:
                    cb(ts, ins or None, removed or None)

    def _probe_aux_keys(self, i: int) -> list:
        """Sorted non-timer aux keys for endpoint i, discovered by tracing
        the impl's aux output structure once (abstract eval, no device)."""
        ep = self.endpoints[i]
        impl = ep.impl_factory()
        B = self.junction.batch_size
        schema = self.junction.schema
        batch = schema.empty_batch(B)
        st = ep.init_state(0)
        tst = {}
        for e2 in self.endpoints:
            tst.update(e2.qr._collect_table_states())
        closed = jax.eval_shape(
            lambda s, t, bb: impl(s, t, bb, np.int64(0))[3], st, tst, batch
        )
        return sorted(
            k
            for k in closed.keys()
            if k != "next_timer" and not k.startswith("__lin")
        )
