"""Named windows: `define window W (...) <window> [output <events>]`.

Reference: core/window/Window.java:63-300 — a shared window processor; queries
insert into it, read its emission stream, join against its live buffer
(find :261), and pull it in store queries. Here the buffer is one shared
device-state pytree owned by this runtime; its emission stream is an output
junction; joins/store-queries read the live state through the same
findable-state threading used for tables.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.event import (
    EventBatch,
    KIND_CURRENT,
    KIND_EXPIRED,
    StreamSchema,
)
from siddhi_tpu.core.executor import Scope
from siddhi_tpu.core.flow import Flow
from siddhi_tpu.core.windows import make_window
from siddhi_tpu.query_api.definition import WindowDefinition


class NamedWindow:
    """Shared window processor + live findable buffer."""

    is_named_window = True

    def __init__(self, definition: WindowDefinition, interner):
        if definition.window is None:
            raise SiddhiAppCreationError(
                f"window '{definition.id}' needs a window type, "
                "e.g. define window W (...) length(10)"
            )
        self.definition = definition
        self.window_id = definition.id
        self.schema = StreamSchema(
            definition.id, [(a.name, a.type) for a in definition.attributes]
        )
        scope = Scope(interner)
        scope.add_stream(definition.id, self.schema.attr_types)
        self.stage = make_window(
            definition.window, self.schema, definition.id, scope
        )
        self.out_events = definition.output_events  # current | expired | all
        self.state = self.stage.init_state()
        self.needs_scheduler = self.stage.needs_scheduler
        cron = getattr(self.stage, "cron_schedule", None)
        self.host_next_timer = cron.next_fire_ms if cron is not None else None
        self.out_junction = None  # wired by the app runtime
        self.timer_target = None
        self._step = jax.jit(self._step_impl)

    def describe_state(self) -> dict:
        """Introspection: the shared buffer's type/fill/capacity plus this
        runtime's wiring (see observability/introspect.py)."""
        d = self.stage.describe_state(self.state)
        d["output_events"] = self.out_events
        return d

    # findable protocol (shared with InMemoryTable)
    @property
    def table_id(self) -> str:
        return self.window_id

    def view(self, state):
        return self.stage.view(state)

    def _step_impl(self, state, batch: EventBatch, now):
        flow = Flow(batch=batch, ref=self.window_id, now=now)
        state, out_flow = self.stage.apply(state, flow)
        b = out_flow.batch
        # `output current|expired events` narrows what downstream queries see
        # (reference: Window.java outputEventType dispatch)
        if self.out_events == "current":
            keep = b.kind != np.int8(KIND_EXPIRED)
        elif self.out_events == "expired":
            keep = b.kind != np.int8(KIND_CURRENT)
        else:
            keep = jnp.ones_like(b.valid)
        out = EventBatch(b.ts, b.kind, b.valid & keep, b.cols)
        return state, out, out_flow.aux

    def receive(self, batch: EventBatch, now: int):
        """Process inserts (or a TIMER batch); caller holds the app lock."""
        self.state, out, aux = self._step(
            self.state, batch, jnp.asarray(now, dtype=jnp.int64)
        )
        return out, aux
