"""Snapshot / persistence: checkpoint and restore of all carried state.

Reference: util/snapshot/SnapshotService.java:45-520 — walks every registered
`Snapshotable` (window queues, NFA token lists, tables, aggregator buckets,
rate limiters) under the ThreadBarrier, Java-serializes a nested map;
util/persistence/{InMemory,FileSystem,IncrementalFileSystem}PersistenceStore
keep revisions named `<timestamp>_<appName>`; restore paths
SiddhiAppRuntime.restore/restoreRevision/restoreLastRevision (:560-600).

Here every stateful component's carried state is a device pytree; a snapshot
is the pytree forest pulled to host numpy plus the host-side bits (intern
table, rate-limiter buffers), pickled. Incremental snapshots store only the
leaves that changed since the previous full snapshot (the analog of the
reference's base/delta split over table operation logs).
"""

from __future__ import annotations

import io
import os
import pickle
import re
import threading
import time
from typing import Optional

import jax
import numpy as np


# ---------------------------------------------------------------------------
# persistence stores
# ---------------------------------------------------------------------------


class InMemoryPersistenceStore:
    """reference: util/persistence/InMemoryPersistenceStore.java:30."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._data: dict[str, dict[str, bytes]] = {}

    def save(self, app_name: str, revision: str, snapshot: bytes) -> None:
        with self._lock:
            self._data.setdefault(app_name, {})[revision] = snapshot

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(app_name, {}).get(revision)

    def get_last_revision(self, app_name: str) -> Optional[str]:
        with self._lock:
            revs = self._data.get(app_name)
            if not revs:
                return None
            return max(revs, key=lambda r: int(r.split("_", 1)[0]))

    def list_revisions(self, app_name: str) -> list[str]:
        with self._lock:
            return sorted(
                self._data.get(app_name, {}), key=lambda r: int(r.split("_", 1)[0])
            )

    def clear_all_revisions(self, app_name: str) -> None:
        with self._lock:
            self._data.pop(app_name, None)

    def delete_revision(self, app_name: str, revision: str) -> None:
        """Drop one revision (auto-checkpoint retention pruning — see
        core/supervision.prune_revisions)."""
        with self._lock:
            self._data.get(app_name, {}).pop(revision, None)


class FileSystemPersistenceStore:
    """reference: util/persistence/FileSystemPersistenceStore.java:32."""

    def __init__(self, base_path: str) -> None:
        self.base_path = base_path

    def _dir(self, app_name: str) -> str:
        return os.path.join(self.base_path, app_name)

    def save(self, app_name: str, revision: str, snapshot: bytes) -> None:
        d = self._dir(app_name)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, revision), "wb") as f:
            f.write(snapshot)

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        p = os.path.join(self._dir(app_name), revision)
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()

    def get_last_revision(self, app_name: str) -> Optional[str]:
        d = self._dir(app_name)
        if not os.path.isdir(d):
            return None
        revs = [f for f in os.listdir(d) if re.match(r"^\d+_", f)]
        if not revs:
            return None
        return max(revs, key=lambda r: int(r.split("_", 1)[0]))

    def list_revisions(self, app_name: str) -> list[str]:
        d = self._dir(app_name)
        if not os.path.isdir(d):
            return []
        return sorted(
            (f for f in os.listdir(d) if re.match(r"^\d+_", f)),
            key=lambda r: int(r.split("_", 1)[0]),
        )

    def clear_all_revisions(self, app_name: str) -> None:
        d = self._dir(app_name)
        if os.path.isdir(d):
            for f in os.listdir(d):
                os.unlink(os.path.join(d, f))

    def delete_revision(self, app_name: str, revision: str) -> None:
        """Drop one revision (auto-checkpoint retention pruning — see
        core/supervision.prune_revisions)."""
        p = os.path.join(self._dir(app_name), revision)
        if os.path.exists(p):
            os.unlink(p)


class IncrementalFileSystemPersistenceStore(FileSystemPersistenceStore):
    """Marker subclass: SnapshotService stores base + delta revisions here
    (reference: IncrementalFileSystemPersistenceStore)."""

    incremental = True


# ---------------------------------------------------------------------------
# snapshot service
# ---------------------------------------------------------------------------


def _to_host(tree):
    # OWNING copies, never views: np.asarray over a jax array can be
    # zero-copy on CPU backends, leaving the snapshot (and the incremental
    # delta base kept in `_last_full`) viewing the live XLA buffer — which
    # the next DONATED dispatch frees out from under it (flaky reads, then
    # a crash when the view outlives the backend)
    return jax.tree_util.tree_map(lambda x: np.array(x, copy=True), tree)


def _to_device(tree):
    import jax.numpy as jnp

    # copy=True: jnp.asarray may alias the unpickled host buffer on CPU,
    # and the restored state's first donated dispatch would then free
    # memory numpy still owns (the restore-then-fused-send hazard)
    return jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True), tree)


def _flat_with_paths(tree) -> dict:
    """{path_str: leaf} using jax's path-aware flatten (structure-exact)."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return {jax.tree_util.keystr(path): leaf for path, leaf in leaves}


def merge_snapshot_interner(interner, payload: dict) -> None:
    """Install a snapshot payload's intern table into `interner`: restored
    states carry interned string ids minted by the CHECKPOINTING process,
    so they must resolve to the original strings here. A conflicting id
    raises rather than silently mis-decoding. Shared by
    `SnapshotService.restore` and the churn state-seeding path
    (core/churn._seed_query_state)."""
    for i, v in enumerate(payload["interner"], start=1):
        if i < len(interner._from_id):
            if interner._from_id[i] != v:
                raise ValueError(
                    f"intern table conflict at id {i}: "
                    f"{interner._from_id[i]!r} != {v!r}"
                )
        else:
            interner._to_id[v] = i
            interner._from_id.append(v)


def merge_snapshot_elements(payloads: list) -> tuple:
    """Fold one full payload plus trailing incremental deltas into
    (elements, rates) — THE base+delta merge, shared by
    `SnapshotService.restore` and the churn seeding path."""
    if payloads[0]["type"] != "full":
        raise ValueError("restore needs a full snapshot first")
    elements = dict(payloads[0]["elements"])
    rates = dict(payloads[0].get("rates", {}))
    for p in payloads[1:]:
        if p["type"] != "incremental":
            raise ValueError("later snapshots must be incremental")
        for k, changed in p["delta"].items():
            if k not in elements:
                continue
            paths, treedef = jax.tree_util.tree_flatten_with_path(elements[k])
            leaves = [
                changed.get(jax.tree_util.keystr(path), leaf)
                for path, leaf in paths
            ]
            elements[k] = jax.tree_util.tree_unflatten(treedef, leaves)
        rates.update(p.get("rates", {}))
    return elements, rates


class SnapshotService:
    """reference: util/snapshot/SnapshotService.java — here the registry is
    the app runtime's component maps; the app process lock is the barrier."""

    def __init__(self, app_runtime) -> None:
        self.rt = app_runtime
        self._last_full: Optional[dict] = None  # {element: {path: leaf}}
        # base STAGED by full_snapshot(track_base=True), promoted to
        # _last_full only by commit_base() — i.e. only once the caller has
        # actually persisted the full payload. Committing eagerly would,
        # after one failed save, leave every later cycle emitting deltas
        # against a base revision that never reached the store (restore
        # then silently no-ops or applies deltas to the wrong base).
        self._pending_base: Optional[dict] = None

    # ---- collection -------------------------------------------------------

    def _elements(self) -> dict:
        """Every stateful component's live state, keyed by stable element id."""
        rt = self.rt
        out: dict[str, object] = {}
        import copy

        for qid, qr in rt.queries.items():
            if qr.state is not None:
                ks = getattr(qr, "_keyshard", None)
                if ks is not None:
                    # canonical single-device form (parallel/keyshard.py):
                    # mesh-size independent, so a restore re-hashes keys to
                    # whatever mesh the restoring app runs on (rebalance)
                    out[f"query:{qid}"] = ks.export_state(qr.state)
                else:
                    out[f"query:{qid}"] = qr.state
            rl = getattr(qr, "rate_limiter", None)
            if rl is not None:
                # deep copy: the live buffers keep mutating once the process
                # lock is released, while pickling happens outside it
                out[f"rate:{qid}"] = copy.deepcopy(dict(vars(rl)))
        for tid, t in rt.tables.items():
            out[f"table:{tid}"] = t.state
        for wid, nw in rt.named_windows.items():
            out[f"window:{wid}"] = nw.state
        for aid, ar in rt.aggregations.items():
            out[f"aggregation:{aid}"] = ar.state
        for i, pr in enumerate(rt.partitions):
            out[f"partition:{i}:keys"] = pr.ptable
        return out

    def _restore_elements(self, elements: dict) -> None:
        rt = self.rt
        for key, value in elements.items():
            kind, _, name = key.partition(":")
            if kind == "query":
                qr = rt.queries.get(name)
                if qr is not None:
                    ks = getattr(qr, "_keyshard", None)
                    if ks is not None:
                        # re-hash the canonical group table onto THIS mesh
                        qr.state = ks.import_state(value)
                    else:
                        qr.state = _to_device(value)
            elif kind == "rate":
                qr = rt.queries.get(name)
                rl = getattr(qr, "rate_limiter", None) if qr else None
                if rl is not None:
                    vars(rl).update(value)
            elif kind == "table":
                t = rt.tables.get(name)
                if t is not None:
                    t.state = _to_device(value)
            elif kind == "window":
                nw = rt.named_windows.get(name)
                if nw is not None:
                    nw.state = _to_device(value)
            elif kind == "aggregation":
                ar = rt.aggregations.get(name)
                if ar is not None:
                    ar.state = _to_device(value)
            elif kind == "partition":
                idx = int(name.split(":")[0])
                if idx < len(rt.partitions):
                    rt.partitions[idx].ptable = _to_device(value)

    # ---- full / incremental snapshots -------------------------------------

    def full_snapshot(self, track_base: bool = False) -> bytes:
        with self.rt._process_lock:  # the reference's ThreadBarrier stop-world
            all_elems = self._elements()
            elements = {
                k: _to_host(v) for k, v in all_elems.items()
                if not k.startswith("rate:")
            }
            rates = {k: v for k, v in all_elems.items() if k.startswith("rate:")}
            interner = list(self.rt.interner._from_id[1:])
        if track_base:
            # deltas are diffed against the last PERSISTED full snapshot only
            # (a bytes-API snapshot must not shift the delta base) — staged
            # here, promoted by commit_base() after the save succeeds
            self._pending_base = {
                k: _flat_with_paths(v) for k, v in elements.items()
            }
        payload = {
            "type": "full",
            "app": self.rt.name,
            "time": int(time.time() * 1000),
            "interner": interner,
            "elements": elements,
            "rates": rates,
        }
        buf = io.BytesIO()
        pickle.dump(payload, buf, protocol=pickle.HIGHEST_PROTOCOL)
        return buf.getvalue()

    def commit_base(self) -> None:
        """Promote the base staged by `full_snapshot(track_base=True)` —
        call ONLY after the payload actually reached the store."""
        if self._pending_base is not None:
            self._last_full = self._pending_base
            self._pending_base = None

    def incremental_snapshot(self) -> bytes:
        """Leaves changed since the last full snapshot (falls back to full
        when no base exists) — the analog of the reference's base/delta split."""
        if self._last_full is None:
            return self.full_snapshot(track_base=True)
        with self.rt._process_lock:
            all_elems = self._elements()
            elements = {
                k: _to_host(v) for k, v in all_elems.items()
                if not k.startswith("rate:")
            }
            rates = {k: v for k, v in all_elems.items() if k.startswith("rate:")}
            interner = list(self.rt.interner._from_id[1:])
        delta: dict[str, dict] = {}
        for k, v in elements.items():
            flat = _flat_with_paths(v)
            base = self._last_full.get(k, {})
            changed = {
                p: leaf
                for p, leaf in flat.items()
                if p not in base
                or not isinstance(leaf, np.ndarray)
                or base[p].shape != leaf.shape
                or not np.array_equal(base[p], leaf, equal_nan=True)
            }
            if changed:
                delta[k] = changed
        payload = {
            "type": "incremental",
            "app": self.rt.name,
            "time": int(time.time() * 1000),
            "interner": interner,
            "delta": delta,
            "rates": rates,
        }
        buf = io.BytesIO()
        pickle.dump(payload, buf, protocol=pickle.HIGHEST_PROTOCOL)
        return buf.getvalue()

    # ---- restore -----------------------------------------------------------

    def restore(self, *snapshots: bytes) -> None:
        """Restore a full snapshot followed by incremental deltas, in order."""
        if not snapshots:
            return
        payloads = [pickle.loads(s) for s in snapshots]
        with self.rt._process_lock:
            # interner: restored ids must resolve to their original strings
            merge_snapshot_interner(self.rt.interner, payloads[-1])
            elements, rates = merge_snapshot_elements(payloads)
            self._restore_elements(elements)
            self._restore_elements(rates)
