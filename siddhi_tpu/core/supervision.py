"""Supervised runtime: auto-checkpoint, crash detection, and restart.

The engine already has the recovery primitives — full checkpoint/restore
(`core/persistence.SnapshotService`, `persist()`/`restore_revision()`), a
restart-surviving error store with `replay_errors()`, and health signals on
every junction — but nothing *drives* them: checkpoints are manual and a
poisoned drain worker or fatal dispatch error leaves the app dead until a
human intervenes. This module closes the loop:

- `@app:persist(interval='30 sec', keep='5')` rides the app scheduler to
  call `persist()` periodically and prune retained revisions to the last N
  (`AutoPersist`; validated as SA126, shared rule set with the analyzer).
- `manager.supervise()` starts one `Supervisor` thread per manager that
  watches the health signals that already exist — unguarded dispatch
  failures and worker errors (`StreamJunction.on_fatal`), @async drain
  worker death, pipeline drain-thread death — and on crash executes
  shutdown -> rebuild the runtime from the retained AST ->
  `restore_last_revision()` -> `replay_errors()` for that app -> resume,
  with `BackoffRetryCounter`-capped attempts per `@app:restart(...)`
  (SA127). Restart events surface in `/status`, Prometheus
  (`siddhi_supervisor_restarts_total`), and the selfmon stream.

Determinism note: the restart sequence loses nothing that reached a
checkpoint or the error store — events processed after the last checkpoint
but before the crash are at-most-once unless their failure path stored
them (`@OnError(action='STORE')` / sink `on.error='STORE'`), which is the
zero-loss contract the chaos harness (`siddhi_tpu/testing/faults.py`,
`tools/chaos_smoke.py`) proves end-to-end.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger(__name__)

_MIN_PERSIST_INTERVAL_MS = 50


def _sole_positional(ann):
    """The value of a single UNKEYED element (`@app:restart('never')`),
    else None. NOT `ann.element(None)`: that falls back to a KEYED single
    element's value, so `@app:persist(keep='5')` would resolve keep as a
    5 ms interval and `@app:restart(max.attempts='5')` as policy='5'."""
    if len(ann.elements) == 1 and ann.elements[0][0] is None:
        return ann.elements[0][1]
    return None


def _parse_time_ms(v) -> Optional[int]:
    """'30 sec' / '500 millisec' / bare integer ms -> ms, None if malformed."""
    from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

    s = str(v).strip()
    try:
        return int(s)
    except ValueError:
        try:
            return SiddhiCompiler.parse_time_constant(s)
        except Exception:
            return None


# ---------------------------------------------------------------------------
# @app:persist — auto-checkpoint (SA126 shares these rules)
# ---------------------------------------------------------------------------


def iter_persist_annotation_problems(ann):
    """Yield one message per `@app:persist` problem — THE validation rules,
    shared by the runtime resolver (raises on the first) and the analyzer's
    SA126 diagnostic (reports them all)."""
    for k, v in ann.elements:
        if k == "interval" or (k is None and len(ann.elements) == 1):
            ms = _parse_time_ms(v)
            if ms is None or ms < _MIN_PERSIST_INTERVAL_MS:
                yield (
                    f"@app:persist interval '{v}' must be a time constant of "
                    f"at least {_MIN_PERSIST_INTERVAL_MS} millisec "
                    "(e.g. '30 sec')"
                )
        elif k == "keep":
            try:
                keep = int(str(v).strip())
            except ValueError:
                keep = 0
            if keep < 1:
                yield (
                    f"@app:persist keep '{v}' must be a positive revision "
                    "count (e.g. keep='5')"
                )
        else:
            yield (
                f"unknown @app:persist option '{k if k is not None else v}' "
                "(expected interval, keep)"
            )


def resolve_persist_annotation(ann) -> tuple[int, Optional[int]]:
    """(interval_ms, keep) for one `@app:persist` annotation. Raises
    SiddhiAppCreationError on malformed options — the runtime analog of the
    analyzer's SA126."""
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    for problem in iter_persist_annotation_problems(ann):
        raise SiddhiAppCreationError(problem)
    v = ann.element("interval") or _sole_positional(ann)
    interval = _parse_time_ms(v) if v is not None else 30_000
    keep = ann.element("keep")
    return interval, (int(keep) if keep is not None else None)


def prune_revisions(store, app_name: str, keep: int) -> list[str]:
    """Drop all but the newest `keep` revisions; returns what was pruned.
    For incremental stores the newest FULL snapshot at-or-before the oldest
    kept revision is retained too — it is the base every kept delta replays
    from (`SiddhiAppRuntime._incremental_chain`). Stores without
    `delete_revision` are left untouched."""
    delete = getattr(store, "delete_revision", None)
    if delete is None:
        return []
    revs = store.list_revisions(app_name)
    if len(revs) <= keep:
        return []
    drop = revs[: len(revs) - keep]
    if getattr(store, "incremental", False):
        import pickle

        base = None
        for r in revs[: len(revs) - keep + 1]:  # up to and incl. oldest kept
            data = store.load(app_name, r)
            if data is None:
                continue
            try:
                if pickle.loads(data)["type"] == "full":
                    base = r
            except Exception:
                continue
        drop = [r for r in drop if r != base]
    for r in drop:
        delete(app_name, r)
    return drop


class AutoPersist:
    """Recurring scheduler target calling `runtime.persist()` every
    `interval_ms` and pruning retained revisions to the last `keep` (owned
    by SiddhiAppRuntime, armed at start() — mirrors SelfMonitor)."""

    def __init__(self, runtime, interval_ms: int, keep: Optional[int]):
        self.runtime = runtime
        self.interval_ms = int(interval_ms)
        self.keep = keep
        self.persists = 0
        self.failures = 0
        self.pruned = 0
        self.last_revision: Optional[str] = None
        self.last_error: Optional[str] = None
        # ONE stable target: the scheduler dedups pending fires by id(target)
        self._target = self._fire

    def start(self) -> None:
        rt = self.runtime
        rt._scheduler.start()
        rt._scheduler.notify_at(rt.clock() + self.interval_ms, self._target)

    def _fire(self, t_ms: int) -> None:
        rt = self.runtime
        if not rt._running:
            return
        try:
            self.last_revision = rt.persist()
            if self.keep is not None:
                self.pruned += len(
                    prune_revisions(
                        rt.manager.persistence_store, rt.name, self.keep
                    )
                )
            # incremented last: observers polling `persists` may assume the
            # cycle's retention pruning has already happened and the error
            # field reflects this cycle
            self.last_error = None
            self.persists += 1
        except Exception as e:
            # a failing store (disk full, injected persist_save fault) must
            # not kill the scheduler thread or stop future attempts
            self.failures += 1
            self.last_error = f"{type(e).__name__}: {e}"
            log.exception("auto-persist for app '%s' failed", rt.name)
        finally:
            rt._scheduler.notify_at(t_ms + self.interval_ms, self._target)

    def describe_state(self) -> dict:
        d = {
            "interval_ms": self.interval_ms,
            "keep": self.keep,
            "persists": self.persists,
            "failures": self.failures,
            "pruned": self.pruned,
        }
        if self.last_revision is not None:
            d["last_revision"] = self.last_revision
        if self.last_error is not None:
            d["last_error"] = self.last_error
        return d


# ---------------------------------------------------------------------------
# @app:restart — restart policy (SA127 shares these rules)
# ---------------------------------------------------------------------------


@dataclass
class RestartPolicy:
    policy: str = "on-failure"  # on-failure | never
    max_attempts: int = 3
    backoff_cap_ms: Optional[int] = None
    reset_after_ms: int = 300_000  # healthy this long -> attempt streak resets


_RESTART_POLICIES = ("on-failure", "never")


def iter_restart_annotation_problems(ann):
    """Yield one message per `@app:restart` problem (SA127 + runtime)."""
    for k, v in ann.elements:
        if k == "policy" or (k is None and len(ann.elements) == 1):
            if str(v).strip().lower() not in _RESTART_POLICIES:
                yield (
                    f"@app:restart policy '{v}' must be one of "
                    f"{_RESTART_POLICIES}"
                )
        elif k == "max.attempts":
            try:
                n = int(str(v).strip())
            except ValueError:
                n = 0
            if n < 1:
                yield (
                    f"@app:restart max.attempts '{v}' must be a positive "
                    "integer"
                )
        elif k in ("backoff", "reset.after"):
            if _parse_time_ms(v) is None:
                yield (
                    f"@app:restart {k} '{v}' must be a time constant "
                    "(e.g. '5 sec')"
                )
        else:
            yield (
                f"unknown @app:restart option '{k if k is not None else v}' "
                "(expected policy, max.attempts, backoff, reset.after)"
            )


def resolve_restart_annotation(ann) -> RestartPolicy:
    """RestartPolicy from `@app:restart(...)`. Raises SiddhiAppCreationError
    on malformed options — the runtime analog of SA127."""
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    for problem in iter_restart_annotation_problems(ann):
        raise SiddhiAppCreationError(problem)
    rp = RestartPolicy()
    v = ann.element("policy") or _sole_positional(ann)
    if v is not None:
        rp.policy = str(v).strip().lower()
    v = ann.element("max.attempts")
    if v is not None:
        rp.max_attempts = int(v)
    v = ann.element("backoff")
    if v is not None:
        rp.backoff_cap_ms = _parse_time_ms(v)
    v = ann.element("reset.after")
    if v is not None:
        rp.reset_after_ms = _parse_time_ms(v)
    return rp


# ---------------------------------------------------------------------------
# health signals
# ---------------------------------------------------------------------------


_OWNED = threading.local()


class failure_ownership:
    """Context manager suppressing `AppHealth.mark_fatal` on this thread:
    entered by callers that CATCH AND HANDLE dispatch failures themselves —
    a source delivering under its own `on.error` policy, or an error-replay
    loop whose caller keeps the entry on failure. Without it, a failure the
    upstream policy fully owns (stored, routed, logged) would still flag
    the app as crashed and a supervised runtime would restart — rolling
    state back over a handled poison payload, potentially forever."""

    def __enter__(self):
        _OWNED.depth = getattr(_OWNED, "depth", 0) + 1
        return self

    def __exit__(self, *exc) -> None:
        _OWNED.depth -= 1


def failures_owned() -> bool:
    return getattr(_OWNED, "depth", 0) > 0


class AppHealth:
    """Per-app crash-signal collector. `mark_fatal` is the junction
    `on_fatal` hook — called on unguarded dispatch failures and worker
    errors; it never raises and never blocks (one append + one notify)."""

    def __init__(self, app_name: str, notify) -> None:
        self.app_name = app_name
        self._notify = notify  # Supervisor wake-up
        self.fatal = collections.deque(maxlen=32)  # (ts_ms, who, error)
        self.flagged = False
        # black-box trigger hook (observability/blackbox.py), wired by
        # Supervisor.attach when the app is @app:blackbox-armed: a fatal
        # signal freezes a crash incident before the restart tears the
        # runtime (and its rings) down. The recorder's debounce absorbs
        # the overlap with the junction-level crash hook.
        self.on_incident = None

    def mark_fatal(self, exc: BaseException, who: str) -> None:
        if failures_owned():
            return  # an upstream on.error policy will capture this failure
        try:
            oi = self.on_incident
            if oi is not None:
                oi("crash", f"{who}: {type(exc).__name__}: {exc}")
            self.fatal.append(
                (int(time.time() * 1000), who, f"{type(exc).__name__}: {exc}")
            )
            self.flagged = True
            self._notify()
        except Exception:  # pragma: no cover - must never re-raise mid-crash
            pass

    def describe_state(self) -> dict:
        return {
            "flagged": self.flagged,
            "fatal_signals": len(self.fatal),
            "last_fatal": list(self.fatal)[-1] if self.fatal else None,
        }


def _incident_tag(rt) -> str:
    """` [incident <id>]` when the crashed runtime froze a black-box
    bundle for this episode — stamped into the supervisor's restart
    records so /status.json links a crash to its post-mortem on disk."""
    bb = getattr(rt, "_blackbox", None)
    iid = getattr(bb, "last_incident_id", None) if bb is not None else None
    return f" [incident {iid}]" if iid else ""


def _probe_runtime(rt) -> Optional[str]:
    """Liveness probe beyond explicit signals: a dead @async drain worker or
    a dead pipeline drain thread means events queue forever with nobody
    draining — the junction never reports it (the thread is simply gone)."""
    for sid, j in list(rt.junctions.items()):
        if j.is_async:
            workers = getattr(j, "_workers", [])
            if workers and not any(t.is_alive() for t in workers):
                return f"stream '{sid}': every async drain worker is dead"
        fi = j.fused_ingest
        pl = getattr(fi, "pipeline", None) if fi is not None else None
        if pl is not None:
            t = getattr(pl, "_thread", None)
            if t is not None and not t.is_alive() and not pl._closed:
                return f"stream '{sid}': pipeline drain thread is dead"
    return None


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------


class _StableInputHandler:
    """Restart-stable ingress facade: resolves the app's CURRENT runtime on
    every call, so a handle obtained before a supervised restart keeps
    working after it (the raw InputHandler binds the dead junction)."""

    def __init__(self, manager, app_name: str, stream_id: str) -> None:
        self._manager = manager
        self._app = app_name
        self._sid = stream_id

    def _h(self):
        rt = self._manager.get_siddhi_app_runtime(self._app)
        if rt is None:
            from siddhi_tpu.core.errors import DefinitionNotExistError

            raise DefinitionNotExistError(
                f"no app '{self._app}' on this manager"
            )
        return rt.get_input_handler(self._sid)

    def send(self, data, timestamp=None):
        return self._h().send(data, timestamp)

    def send_many(self, rows, timestamps=None):
        return self._h().send_many(rows, timestamps)

    def send_columns(self, timestamps, cols, now=None):
        return self._h().send_columns(timestamps, cols, now)


class Supervisor:
    """One per manager (`manager.supervise()`): watches every attached app's
    health signals and liveness, restarts crashed apps under their
    `@app:restart` policy, and surfaces restart events in `/status`,
    Prometheus, and selfmon."""

    def __init__(self, manager, poll_interval_s: float = 0.25) -> None:
        self.manager = manager
        self.poll_interval_s = float(poll_interval_s)
        self._cv = threading.Condition()
        self._stop = False
        self._health: dict[str, AppHealth] = {}
        self._attempts: dict[str, int] = {}  # restart streak per app
        self._last_restart_ms: dict[str, int] = {}
        self.restarts: dict[str, int] = {}  # app -> successful restarts
        self.gave_up: dict[str, str] = {}  # app -> reason
        # apps whose last restart ATTEMPT failed (e.g. restore raised): the
        # rebuilt runtime is down (_running=False), so liveness probing
        # can't see it — this map keeps the next poll retrying until the
        # attempt budget runs out instead of abandoning the app
        self._down: dict[str, str] = {}  # app -> reason
        # first sighting of the CURRENT crash episode (cleared on a
        # successful restart): the reset-after-healthy check measures the
        # healthy stretch up to here, not wall time since the last attempt
        # — an app sitting dead through its backoff window is not healthy
        self._crash_seen_ms: dict[str, int] = {}
        self._rebuilding: Optional[str] = None  # app mid-_do_restart
        self.events = collections.deque(maxlen=64)  # (ts, app, what)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="siddhi-supervisor"
        )
        self._thread.start()

    # ---- wiring ----------------------------------------------------------

    def attach(self, rt) -> None:
        """Supervise one runtime: install the AppHealth hook on every
        junction (lazily-created junctions pick it up in `_junction()`)."""
        if self._rebuilding != rt.name:
            # an OPERATOR redeploy under the same name starts a fresh
            # supervision life: the exhausted-budget verdict and the
            # attempt streak belong to the replaced deployment. The
            # supervisor's own rebuild (create inside _do_restart) must
            # NOT reset them, or max.attempts could never exhaust.
            self.gave_up.pop(rt.name, None)
            self._down.pop(rt.name, None)
            self._attempts.pop(rt.name, None)
            self._crash_seen_ms.pop(rt.name, None)
        health = AppHealth(rt.name, self._wake)
        bb = getattr(rt, "_blackbox", None)
        if bb is not None:
            # a fatal signal freezes a crash incident bundle before the
            # restart tears the rings down (observability/blackbox.py)
            health.on_incident = bb.fire
        self._health[rt.name] = health
        rt._health = health
        for j in list(rt.junctions.values()):
            j.on_fatal = health.mark_fatal

    def detach(self, app_name: str) -> None:
        self._health.pop(app_name, None)

    def input_handler(self, app_name: str, stream_id: str):
        """A restart-stable input handler for `stream_id` of `app_name`."""
        return _StableInputHandler(self.manager, app_name, stream_id)

    def _wake(self) -> None:
        with self._cv:
            self._cv.notify()

    # ---- the loop --------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                if self._stop:
                    return
                self._cv.wait(timeout=self.poll_interval_s)
                if self._stop:
                    return
            try:
                self._check_all()
            except Exception:  # pragma: no cover - loop must survive
                log.exception("supervisor check failed")

    def _check_all(self) -> None:
        for name, health in list(self._health.items()):
            if name == self._rebuilding:
                # mid-rebuild (our own restart, or an operator redeploy's
                # atomic swap — core/churn.redeploy): the teardown below
                # this guard is intentional, not a crash to race a restart
                # against
                continue
            rt = self.manager.get_siddhi_app_runtime(name)
            if rt is None:
                # intentionally shut down and deregistered
                self.detach(name)
                continue
            if getattr(rt, "_health", None) is not health:
                continue  # replaced mid-restart; the new health is tracked
            if name in self.gave_up:
                continue
            if name in self._down:
                # the last restart ATTEMPT failed and left the app down
                # (_running=False) — keep retrying against the remaining
                # attempt budget rather than abandoning it
                self._restart(name, rt, self._down[name])
                continue
            if not rt._running:
                continue  # not started (or stopping) — nothing to probe
            reason = None
            if health.flagged:
                reason = (
                    health.fatal[-1][2] if health.fatal else "fatal signal"
                )
            else:
                reason = _probe_runtime(rt)
            if reason is not None:
                self._restart(name, rt, reason)

    # ---- restart ---------------------------------------------------------

    def _policy_for(self, rt) -> RestartPolicy:
        from siddhi_tpu.query_api.annotation import find_annotation

        ann = find_annotation(rt.app.annotations, "app:restart")
        if ann is None:
            return RestartPolicy()
        try:
            return resolve_restart_annotation(ann)
        except Exception:  # validated at creation; belt and braces
            return RestartPolicy()

    def _restart(self, name: str, rt, reason: str) -> None:
        from siddhi_tpu.core.io import BackoffRetryCounter

        now_ms = int(time.time() * 1000)
        policy = self._policy_for(rt)
        if policy.policy == "never":
            self._down.pop(name, None)
            self.gave_up[name] = f"policy=never ({reason})"
            self.events.append(
                (now_ms, name, f"not restarted: {reason}{_incident_tag(rt)}")
            )
            log.error(
                "supervisor: app '%s' crashed (%s); @app:restart policy is "
                "'never' — leaving it down", name, reason,
            )
            rt.shutdown()
            return
        # streak reset only after a genuinely HEALTHY stretch: from the
        # last restart attempt to the first sighting of THIS crash. Using
        # `now` instead would count backoff/down time as healthy, and a
        # crash-looping app whose backoff ladder reaches reset.after would
        # reset its streak forever — gave_up unreachable.
        seen = self._crash_seen_ms.setdefault(name, now_ms)
        last = self._last_restart_ms.get(name, 0)
        if seen - last > policy.reset_after_ms:
            self._attempts[name] = 0
        attempts = self._attempts.get(name, 0)
        if attempts >= policy.max_attempts:
            self._down.pop(name, None)
            self.gave_up[name] = (
                f"max.attempts={policy.max_attempts} exhausted ({reason})"
            )
            self.events.append(
                (now_ms, name, f"gave up: {reason}{_incident_tag(rt)}")
            )
            log.error(
                "supervisor: app '%s' crashed (%s) but its restart budget "
                "(max.attempts=%d) is exhausted — leaving it down",
                name, reason, policy.max_attempts,
            )
            rt.shutdown()
            return
        # backoff BEFORE the attempt (attempt 0 restarts immediately): the
        # same ladder transports use, capped by @app:restart(backoff=...).
        # A due-time gate, NOT a sleep: the one supervisor thread serves
        # every app on the manager, and a crash-looping app must not hold
        # the others' crash detection hostage for its backoff window — the
        # still-flagged health (or the _down marker) re-enters here on a
        # later poll until the window has elapsed.
        if attempts > 0:
            counter = BackoffRetryCounter(max_interval_ms=policy.backoff_cap_ms)
            iv = 0
            for _ in range(attempts):
                iv = counter.next_interval_ms()
            if now_ms < self._last_restart_ms.get(name, 0) + iv:
                return
        self._attempts[name] = attempts + 1
        self._last_restart_ms[name] = now_ms
        log.warning(
            "supervisor: restarting app '%s' (attempt %d/%d): %s",
            name, attempts + 1, policy.max_attempts, reason,
        )
        try:
            self._do_restart(name, rt)
        except Exception as e:
            # the app is now down with budget left: _down keeps the next
            # poll retrying (the rebuilt-but-unstarted runtime fails the
            # _running liveness probe, so nothing else would re-trigger)
            self._down[name] = f"{type(e).__name__}: {e}"
            self.events.append(
                (
                    now_ms, name,
                    f"restart failed: {type(e).__name__}: {e}"
                    f"{_incident_tag(rt)}",
                )
            )
            log.exception("supervisor: restart of app '%s' failed", name)
            return
        self._down.pop(name, None)
        # this crash episode is over: the next crash is a fresh sighting
        self._crash_seen_ms.pop(name, None)
        self.restarts[name] = self.restarts.get(name, 0) + 1
        self.events.append(
            (now_ms, name, f"restarted: {reason}{_incident_tag(rt)}")
        )

    def _do_restart(self, name: str, rt) -> None:
        """shutdown -> rebuild from the retained AST -> restore the last
        checkpoint -> replay this app's stored errors -> resume."""
        mgr = self.manager
        app_ast = rt.app
        callbacks = list(getattr(rt, "_user_callbacks", []))
        handler = getattr(rt, "_exception_handler", None)
        try:
            rt.shutdown()
        except Exception:
            log.exception(
                "supervisor: shutdown of crashed app '%s' raised; "
                "rebuilding anyway", name,
            )
        # create_siddhi_app_runtime re-attaches supervision (manager hook);
        # _rebuilding tells attach() this is OUR rebuild, not an operator
        # redeploy, so the attempt streak survives the re-attach
        self._rebuilding = name
        try:
            new_rt = mgr.create_siddhi_app_runtime(app_ast)
        finally:
            self._rebuilding = None
        for cb_name, cb in callbacks:
            try:
                new_rt.add_callback(cb_name, cb)
            except Exception:
                log.exception(
                    "supervisor: could not re-register callback '%s' on "
                    "app '%s'", cb_name, name,
                )
        if handler is not None:
            new_rt.set_exception_handler(handler)
        if mgr.persistence_store is not None:
            new_rt.restore_last_revision()
        new_rt.start()
        # replay ONLY this app's entries, without letting a WAIT-blocked
        # sink wedge the supervisor thread
        store = mgr._error_store
        if store is not None:
            entries = store.load(app_name=name)
            if entries:
                n = mgr.replay_errors(entries=entries, skip_unavailable=True)
                log.info(
                    "supervisor: replayed %d/%d stored entries for app '%s'",
                    n, len(entries), name,
                )

    # ---- surfacing -------------------------------------------------------

    def describe_state(self) -> dict:
        return {
            "apps_supervised": sorted(self._health),
            "restarts": dict(self.restarts),
            "restarts_total": sum(self.restarts.values()),
            "gave_up": dict(self.gave_up),
            "down": dict(self._down),
            "events": [list(e) for e in self.events],
        }

    def prometheus_text(self) -> str:
        lines = [
            "# HELP siddhi_supervisor_restarts_total Successful supervised "
            "app restarts",
            "# TYPE siddhi_supervisor_restarts_total counter",
        ]
        apps = set(self._health) | set(self.restarts)
        for app in sorted(apps):
            lines.append(
                f'siddhi_supervisor_restarts_total{{app="{app}"}} '
                f"{self.restarts.get(app, 0)}"
            )
        return "\n".join(lines) + "\n"

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
