"""Attribute types, physical dtype mapping, and host-side string interning.

The reference engine types attributes as STRING/INT/LONG/FLOAT/DOUBLE/BOOL/OBJECT
(reference: siddhi-query-api .../definition/Attribute.java). On TPU we keep the
*logical* type for promotion semantics but map to TPU-friendly physical dtypes:
DOUBLE runs as float32 (TPU has no f64 ALU; tolerance policy documented in
SURVEY.md §7 hard-parts (d)), STRING/OBJECT are dictionary-encoded to int32 ids via
a host-side intern table (equality / group-by work on ids; decoding happens at the
egress boundary).
"""

from __future__ import annotations

import enum
import threading
from typing import Any

import jax.numpy as jnp
import numpy as np


class AttrType(enum.Enum):
    STRING = "string"
    INT = "int"
    LONG = "long"
    FLOAT = "float"
    DOUBLE = "double"
    BOOL = "bool"
    OBJECT = "object"

    def __repr__(self) -> str:  # compact in error messages
        return self.name


# Logical -> physical jnp dtype on device.
PHYSICAL_DTYPE = {
    AttrType.STRING: jnp.int32,   # interned id
    AttrType.INT: jnp.int32,
    AttrType.LONG: jnp.int64,
    AttrType.FLOAT: jnp.float32,
    AttrType.DOUBLE: jnp.float32,  # TPU: no f64; logical DOUBLE tracked separately
    AttrType.BOOL: jnp.bool_,
    AttrType.OBJECT: jnp.int32,   # interned id
}

NUMERIC_TYPES = (AttrType.INT, AttrType.LONG, AttrType.FLOAT, AttrType.DOUBLE)

# Promotion order for arithmetic, mirroring the reference's per-type executor
# selection (reference: core/util/parser/ExpressionParser.java:560+ — DOUBLE wins,
# then FLOAT, then LONG, then INT).
_PROMOTION_ORDER = [AttrType.INT, AttrType.LONG, AttrType.FLOAT, AttrType.DOUBLE]

# Null sentinels: columnar tensors cannot hold Java nulls, so each physical class
# reserves a sentinel. STRING/OBJECT id 0 is always null ("" interns to 1+).
NULL_ID = 0
NULL_INT = np.int32(np.iinfo(np.int32).min)
NULL_LONG = np.int64(np.iinfo(np.int64).min)
# float/double nulls are NaN.


def promote(a: AttrType, b: AttrType) -> AttrType:
    """Binary arithmetic result type, per the reference's executor matrix."""
    if a not in NUMERIC_TYPES or b not in NUMERIC_TYPES:
        raise TypeError(f"cannot apply arithmetic to {a!r} and {b!r}")
    return _PROMOTION_ORDER[max(_PROMOTION_ORDER.index(a), _PROMOTION_ORDER.index(b))]


def is_integral(t: AttrType) -> bool:
    return t in (AttrType.INT, AttrType.LONG)


def null_value(t: AttrType):
    """The device-side sentinel representing null for a logical type."""
    if t in (AttrType.STRING, AttrType.OBJECT):
        return NULL_ID
    if t is AttrType.INT:
        return NULL_INT
    if t is AttrType.LONG:
        return NULL_LONG
    if t in (AttrType.FLOAT, AttrType.DOUBLE):
        return np.float32(np.nan)
    if t is AttrType.BOOL:
        return False  # BOOL has no null on device
    raise TypeError(t)


class InternTable:
    """Bidirectional string/object <-> int32 id table (host side, thread-safe).

    Replaces the reference's boxed Object payloads for STRING/OBJECT attributes.
    id 0 is reserved for null. Objects that are not strings are interned by
    identity-equality via their Python hash/eq.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._to_id: dict[Any, int] = {}
        self._from_id: list[Any] = [None]  # id 0 -> null
        self._snapshot = None  # cached object-array view for lookup_many

    def intern(self, value: Any) -> int:
        if value is None:
            return NULL_ID
        with self._lock:
            ident = self._to_id.get(value)
            if ident is None:
                ident = len(self._from_id)
                self._to_id[value] = ident
                self._from_id.append(value)
                self._snapshot = None  # invalidate lookup_many cache
            return ident

    def lookup(self, ident: int) -> Any:
        return self._from_id[int(ident)]

    def lookup_many(self, ids) -> list:
        """Vectorized id -> value for an integer array (one fancy index
        instead of len(ids) Python calls — the fused egress drain decodes
        hundreds of thousands of interned ids per chunk). The object-array
        snapshot is cached and invalidated by intern()."""
        import numpy as np

        with self._lock:
            table = self._snapshot
            if table is None:
                table = self._snapshot = np.asarray(
                    self._from_id, dtype=object
                )
        return table[np.asarray(ids, dtype=np.int64)].tolist()

    def __len__(self) -> int:
        return len(self._from_id)
