"""Mesh sharding for partitioned queries — the multi-chip execution path.

Reference analog: the reference is single-JVM (SURVEY §2.7); its only data
parallelism is `partition with (key of S)` cloning query graphs per key.
Here that same construct IS the scale-out axis: a PartitionedQueryRuntime
already carries a leading [P] partition axis on every state leaf, so placing
that axis on a `jax.sharding.Mesh` spreads the partitions across devices —
windows/aggregators of different keys advance in parallel on different chips,
with XLA inserting any needed collectives over ICI/DCN.

Usage:

    from jax.sharding import Mesh
    from siddhi_tpu.parallel.mesh import shard_partitioned_query

    mesh = Mesh(np.array(jax.devices()), ("part",))
    sharded = shard_partitioned_query(runtime.queries["q"], mesh)
    outs, aux = sharded.step(batch, now)     # one sharded engine step
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def import_shard_map():
    """Version-tolerant `shard_map` import: newer jax exports it at the top
    level (`jax.shard_map`), older releases keep it under
    `jax.experimental.shard_map`. The seed carried an ImportError here for
    releases without the top-level export."""
    try:
        from jax import shard_map as sm  # jax >= 0.6-ish
    except ImportError:  # pragma: no cover - depends on installed jax
        from jax.experimental.shard_map import shard_map as sm
    return sm


def shard_map_unchecked(fn, mesh, in_specs, out_specs):
    """`shard_map` with replication checking disabled, tolerant of the
    `check_rep` (old) -> `check_vma` (new) kwarg rename."""
    sm = import_shard_map()
    try:
        return sm(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except TypeError:
        return sm(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


@dataclasses.dataclass
class ShardedPartitionedQuery:
    """A partitioned query whose [P] state axis lives across a device mesh."""

    qr: object  # PartitionedQueryRuntime
    mesh: object
    axis: str
    _fn: object
    _ptable: object
    _state: object

    def step(self, batch, now):
        """Run one full partitioned step with the partition axis sharded."""
        self._ptable, self._state, outs, aux = self._fn(
            self._ptable, self._state, batch, jnp.asarray(now, jnp.int64)
        )
        return outs, aux

    @property
    def state(self):
        return self._state

    def total_emitted(self, outs) -> int:
        """psum the per-shard emission counts across the mesh (an explicit
        ICI collective, mostly useful for validation/monitoring)."""
        from jax import lax
        from jax.sharding import PartitionSpec as P

        def count(valid):
            return lax.psum(valid.sum()[None], self.axis)

        counted = shard_map_unchecked(
            count, self.mesh, P(self.axis), P(None)
        )
        return int(counted(outs.valid)[0])


def shard_partitioned_query(
    qr, mesh, axis: Optional[str] = None, routed: bool = True
) -> ShardedPartitionedQuery:
    """Jit a PartitionedQueryRuntime's outer step with its [P] partition axis
    sharded over `mesh`.

    routed=True (default): the BATCH AXIS is sharded too. A replicated
    routing pre-pass (key extraction + slot assignment over the small [B]
    batch) computes each event's owning device by STRIPING slots across the
    mesh — device = slot % D, local state row = slot // D, so the first D
    live keys land on D different chips instead of filling device 0's block
    first — packs per-device sub-batches [D, B] sharded on the mesh axis,
    and a shard_map advances each device's LOCAL partition slice against
    only its own events — each chip decodes B rows, not D*B (the TPU-native
    analog of the reference's per-key routing,
    PartitionStreamReceiver.java:81-140).
    Timer rows are broadcast to every device, interleaved at their original
    row positions so time-driven operators fire in the unsharded order.

    routed=False replicates the batch to every device (the r3 behavior;
    correctness baseline).

    The partition capacity (@app:partitionCapacity) must be divisible by the
    mesh size so every device holds an equal slice of partition slots.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = axis or mesh.axis_names[0]
    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    if qr.p % n_dev != 0:
        raise ValueError(
            f"partition capacity {qr.p} is not divisible by the mesh size "
            f"{n_dev}; set @app:partitionCapacity(size='<multiple of {n_dev}>')"
        )

    shard = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())

    state0 = jax.device_put(qr._fresh(qr.init_state()), shard)
    ptable0 = jax.device_put(
        {
            "keys": jnp.zeros((qr.p,), jnp.int64),
            "used": jnp.zeros((qr.p,), jnp.bool_),
            "n": jnp.zeros((), jnp.int32),
        },
        repl,
    )
    if not routed:
        fn = jax.jit(
            qr._pstep_outer_impl,
            in_shardings=(repl, shard, repl, repl),
            out_shardings=(repl, shard, shard, repl),
        )
        return ShardedPartitionedQuery(qr, mesh, axis, fn, ptable0, state0)

    fn = jax.jit(
        _make_routed_step(qr, mesh, axis, n_dev),
        in_shardings=(repl, shard, repl, repl),
        out_shardings=(repl, shard, shard, repl),
    )
    return ShardedPartitionedQuery(qr, mesh, axis, fn, ptable0, state0)


def _make_routed_step(qr, mesh, axis: str, n_dev: int):
    """Build the routed sharded step (see shard_partitioned_query)."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from siddhi_tpu.core.event import (
        EventBatch,
        KIND_CURRENT,
        KIND_TIMER,
    )
    from siddhi_tpu.core.executor import Env, TS_ATTR
    from siddhi_tpu.ops.group import assign_slots

    D = n_dev
    PL = qr.p // D  # local partition slots per device

    def routed_step(ptable, states, batch: EventBatch, now):
        B = batch.ts.shape[0]
        cols = {(qr.ref, None, n): c for n, c in batch.cols.items()}
        cols[(qr.ref, None, TS_ATTR)] = batch.ts
        env = Env(cols, now=now)
        keys, matched = qr.key_of(env)
        active = batch.valid & (batch.kind == KIND_CURRENT) & matched
        pk, pu, pn, slot, _grp, povf = assign_slots(
            ptable["keys"], ptable["used"], ptable["n"], keys, active
        )
        is_timer = batch.valid & (batch.kind == KIND_TIMER)

        # ---- route the batch axis: device d owns slots {s : s % D == d}
        # (STRIPED, not blocked — first-seen slot allocation hands out low
        # slot numbers first, so a block map slot//PL leaves high devices
        # idle until >PL live keys exist; striping spreads the first D keys
        # across all D devices, the analog of key-hash routing in the
        # reference's PartitionStreamReceiver.java:81-140). Slot s's state
        # lives at block-sharded state row (s % D)*PL + s//D, i.e. device
        # s % D, local row s // D.
        # Each device's sub-batch = its own active rows UNION all timer rows,
        # kept in ORIGINAL row order (a [D, B] mask + per-row cumsum), so
        # timer-driven operators see timers interleaved exactly as the
        # unsharded path does. |actives_d ∪ timers| <= B always, so the
        # sub-batch capacity B can never overflow.
        idx = jnp.arange(B, dtype=jnp.int32)
        dev_of = jnp.where(active & (slot < qr.p), slot % D, D)
        take = (dev_of[None, :] == jnp.arange(D)[:, None]) | is_timer[None, :]
        rank = jnp.cumsum(take.astype(jnp.int32), axis=1) - 1  # [D, B]
        dst = jnp.where(take, jnp.arange(D)[:, None] * B + rank, D * B)
        routed = (
            jnp.full((D * B,), B, jnp.int32)
            .at[dst.reshape(-1)]
            .set(jnp.broadcast_to(idx[None, :], (D, B)).reshape(-1),
                 mode="drop")
            .reshape(D, B)
        )
        pad = routed >= B
        ri = jnp.clip(routed, 0, B - 1)

        def lane(x, fill=0):
            return jnp.where(pad, np.asarray(fill, x.dtype), x[ri])

        r_ts = lane(batch.ts)
        r_kind = lane(batch.kind)
        r_valid = ~pad
        r_cols = {n: lane(c) for n, c in batch.cols.items()}
        r_slot = lane(jnp.where(active, slot, qr.p), fill=qr.p)

        # ---- per-device local advance over its own sub-batch
        def local(states_sl, ts_sl, kind_sl, valid_sl, cols_sl, slot_sl, now_):
            d = lax.axis_index(axis)
            ts1 = ts_sl[0]
            kind1 = kind_sl[0]
            valid1 = valid_sl[0]
            cols1 = {n: c[0] for n, c in cols_sl.items()}
            slot1 = slot_sl[0]
            is_t = valid1 & (kind1 == KIND_TIMER)

            def one(state, p_local):
                gp = p_local * D + d
                v = (valid1 & (slot1 == gp)) | is_t
                b2 = EventBatch(ts1, kind1, v, cols1)
                st, _ts, out, aux = qr._step_impl(state, {}, b2, now_)
                return st, out, aux

            states2, outs, auxs = jax.vmap(one)(
                states_sl, jnp.arange(PL)
            )
            aux_red = {
                k: lax.psum(
                    jnp.asarray(v).astype(jnp.int32).sum(), axis
                )
                > 0
                for k, v in auxs.items()
                if k != "next_timer"
            }
            if "next_timer" in auxs:
                aux_red["next_timer"] = lax.pmin(
                    jnp.min(auxs["next_timer"]), axis
                )
            return states2, outs, aux_red

        local_sharded = shard_map_unchecked(
            local,
            mesh,
            (P(axis), P(axis), P(axis), P(axis), P(axis), P(axis), P()),
            (P(axis), P(axis), P()),
        )
        states2, outs, aux = local_sharded(
            states, r_ts, r_kind, r_valid, r_cols, r_slot, now
        )
        aux = dict(aux)
        aux["partition_overflow"] = (
            jnp.asarray(aux.get("partition_overflow", False)) | povf
        )
        return {"keys": pk, "used": pu, "n": pn}, states2, outs, aux

    return routed_step
