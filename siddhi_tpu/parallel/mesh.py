"""Mesh sharding for partitioned queries — the multi-chip execution path.

Reference analog: the reference is single-JVM (SURVEY §2.7); its only data
parallelism is `partition with (key of S)` cloning query graphs per key.
Here that same construct IS the scale-out axis: a PartitionedQueryRuntime
already carries a leading [P] partition axis on every state leaf, so placing
that axis on a `jax.sharding.Mesh` spreads the partitions across devices —
windows/aggregators of different keys advance in parallel on different chips,
with XLA inserting any needed collectives over ICI/DCN.

Usage:

    from jax.sharding import Mesh
    from siddhi_tpu.parallel.mesh import shard_partitioned_query

    mesh = Mesh(np.array(jax.devices()), ("part",))
    sharded = shard_partitioned_query(runtime.queries["q"], mesh)
    outs, aux = sharded.step(batch, now)     # one sharded engine step
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class ShardedPartitionedQuery:
    """A partitioned query whose [P] state axis lives across a device mesh."""

    qr: object  # PartitionedQueryRuntime
    mesh: object
    axis: str
    _fn: object
    _ptable: object
    _state: object

    def step(self, batch, now):
        """Run one full partitioned step with the partition axis sharded."""
        self._ptable, self._state, outs, aux = self._fn(
            self._ptable, self._state, batch, jnp.asarray(now, jnp.int64)
        )
        return outs, aux

    @property
    def state(self):
        return self._state

    def total_emitted(self, outs) -> int:
        """psum the per-shard emission counts across the mesh (an explicit
        ICI collective, mostly useful for validation/monitoring)."""
        from functools import partial

        from jax import lax, shard_map
        from jax.sharding import PartitionSpec as P

        @partial(
            shard_map, mesh=self.mesh, in_specs=P(self.axis), out_specs=P(None)
        )
        def count(valid):
            return lax.psum(valid.sum()[None], self.axis)

        return int(count(outs.valid)[0])


def shard_partitioned_query(
    qr, mesh, axis: Optional[str] = None
) -> ShardedPartitionedQuery:
    """Jit a PartitionedQueryRuntime's outer step with its [P] partition axis
    sharded over `mesh` and its key table / inputs replicated.

    The partition capacity (@app:partitionCapacity) must be divisible by the
    mesh size so every device holds an equal slice of partition slots.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = axis or mesh.axis_names[0]
    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    if qr.p % n_dev != 0:
        raise ValueError(
            f"partition capacity {qr.p} is not divisible by the mesh size "
            f"{n_dev}; set @app:partitionCapacity(size='<multiple of {n_dev}>')"
        )

    shard = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())

    state0 = jax.device_put(qr._fresh(qr.init_state()), shard)
    ptable0 = jax.device_put(
        {
            "keys": jnp.zeros((qr.p,), jnp.int64),
            "used": jnp.zeros((qr.p,), jnp.bool_),
            "n": jnp.zeros((), jnp.int32),
        },
        repl,
    )
    fn = jax.jit(
        qr._pstep_outer_impl,
        in_shardings=(repl, shard, repl, repl),
        out_shardings=(repl, shard, shard, repl),
    )
    return ShardedPartitionedQuery(qr, mesh, axis, fn, ptable0, state0)
