"""Key-sharded stateful scale-out: group-by aggregation and join state on
the `@app:shard` mesh (axis='keys').

PR 10 sharded partitioned `[P]` state and stateless batch routing; every
non-partitioned group-by aggregation and join window still lived on one
device. This module hashes group keys to mesh devices so each device owns a
DISJOINT key range of the aggregation table:

- `KeyShardedGroupExec` wraps an eligible single-stream grouped query's
  jitted step in a `shard_map` program. Every device sees the full
  replicated micro-batch, runs the (stateless) chain, then masks away rows
  whose group key it does not own — the key-routed pre-pass. The selector
  advances only the owned groups' aggregator lanes. Because emissions are
  POSITIONAL (row b of the output corresponds to row b of the input), the
  merge restores exact order for free: out rows are owner-masked and
  psum-folded across the mesh (the `total_emitted` psum in parallel/mesh.py
  is the seed pattern), reconstructing the unsharded output byte-for-byte —
  float lanes are bitcast to integer bits before the masked psum so -0.0
  and NaN payloads survive exactly.
- `apply_join_mesh` places join window ring buffers across the mesh via
  explicit in/out shardings on the sides' jitted steps (GSPMD): each device
  holds a per-device sub-window and the join probe's cross-device gather is
  realized by the partitioner. The program itself is unchanged, so
  `WindowStage.view_seq()` lineage lanes — and byte parity — are preserved
  trivially.

Eligibility is deliberately narrow (`keyed_shardable`): a plain
windowless grouped query with no host-side ordering state. Everything
else keeps the single-device step and is reported with a reason in
`ShardRuntime.describe_state()["keyshard"]`.

Snapshot SPI (core/persistence.py): `export_state` canonicalizes the
`[D, G]` sharded group table into the SINGLE-device layout, so a snapshot
taken on an 8-device mesh restores onto any mesh size — `import_state`
re-hashes every group key to its new owner. That is how PR 11's
rebalance rides mesh-size changes.

Grounding: the cloud-native pattern-detection framework shards detection
state by key hash (PAPERS.md, arxiv 2401.09960); TiLT's time-centric merge
(arxiv 2301.12030) motivates the positional psum fold.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

KEY_AXIS = "keys"

# splitmix64 finalizer constants — group keys from `mix_keys` pass single
# columns through UN-mixed (ops/group.py), so the owner hash must scramble
# low bits itself or sequential interned ids would stripe the mesh
_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)


def mix64(k):
    """splitmix64 finalizer over uint64 lanes. Works on BOTH numpy and
    jax arrays (same operators, same wraparound) — the device step and the
    host-side snapshot re-hash MUST agree bit-for-bit on ownership."""
    k = k ^ (k >> np.uint64(30))
    k = k * _M1
    k = k ^ (k >> np.uint64(27))
    k = k * _M2
    k = k ^ (k >> np.uint64(31))
    return k


def owner_of(keys, n_devices: int):
    """Owning device index in [0, n_devices) for each int64 group key.
    Dual-use: jnp arrays inside the sharded step, numpy arrays in the
    snapshot import re-hash."""
    return (mix64(keys.astype("uint64")) % np.uint64(n_devices)).astype(
        "int32"
    )


def keyed_shardable(qr) -> tuple[bool, Optional[str]]:
    """(eligible, reason-when-not) for key-sharding one query runtime.

    The contract mirrors `shardable_stateless` (parallel/shard.py) but
    allows exactly ONE kind of cross-batch state: the group-by slot table
    plus its aggregator lanes. A windowless grouped query's per-group
    values depend only on that group's rows, and a group's rows always
    hash to one device — so per-device selectors advancing disjoint key
    ranges reproduce the unsharded output at every owned row position."""
    from siddhi_tpu.core.query_runtime import QueryRuntime

    if type(qr) is not QueryRuntime:
        return False, "not a plain single-stream query runtime"
    sel = qr.selector
    if sel.group is None:
        return False, "no group-by key to shard on"
    if qr.chain.window is not None:
        return False, "windowed chain state is not key-shardable yet"
    if sel.order_by or sel.limit is not None or sel.offset is not None:
        return False, "order by / limit reorders rows across groups"
    if qr.rate_limiter is not None:
        return False, "output rate limiter holds host-side state"
    if qr.table_op is not None or qr.tables:
        return False, "table reads/writes stay single-device"
    if getattr(qr, "join_findables", None):
        return False, "in-condition table probes stay single-device"
    # Byte parity requires every aggregator to be exact under scan-tree
    # reassociation: the owner mask flips non-owned rows inactive, which
    # changes the (active, era, key, idx) sorted layout feeding
    # `segmented_cumsum`, which changes how the blocked scan associates
    # additions. Integer adds and min/max commute exactly; float adds
    # drift by ULPs (observed: 1-ULP avg() divergence at 8 devices).
    from siddhi_tpu.core.aggregators import (
        CountAggregator,
        ExtremeAggregator,
        SumAggregator,
    )
    from siddhi_tpu.core.types import AttrType

    for agg in sel.aggregators:
        if isinstance(agg, (CountAggregator, ExtremeAggregator)):
            continue
        if isinstance(agg, SumAggregator) and agg.type is AttrType.LONG:
            continue
        return False, (
            f"{type(agg).__name__} float arithmetic is "
            "reassociation-sensitive under the key-routed mask"
        )
    return True, None


class KeyShardedGroupExec:
    """Key-sharded execution of one eligible grouped query.

    Owns the mesh, the jitted shard_map step (same 4-arg signature as
    `QueryRuntime._step_impl`, so `receive()`'s timing/writeback path is
    untouched), the `[D]`-stacked initial state, live per-device
    key-occupancy gauges, and the snapshot canonicalize/re-hash pair."""

    def __init__(self, qr, devices):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        self.qr = qr
        self.devices = list(devices)
        self.n = len(self.devices)
        self.mesh = Mesh(np.array(self.devices), (KEY_AXIS,))
        shard = NamedSharding(self.mesh, P(KEY_AXIS))
        repl = NamedSharding(self.mesh, P())
        # donate_argnums matches the unsharded jit: the [D] state updates
        # in place (the first call's host-built state isn't donatable —
        # one ignorable warning, same as the partition mesh path)
        self._jit = jax.jit(
            self._step_impl,
            in_shardings=(shard, repl, repl, repl),
            out_shardings=(shard, repl, repl, repl),
            donate_argnums=(0,),
        )

    # ---- arming ----------------------------------------------------------

    def arm(self) -> None:
        """Swap the query's jitted step for the sharded one. Must run
        before the first receive materializes state (the state layout is
        part of the traced program)."""
        qr = self.qr
        if qr.state is not None:  # pragma: no cover — callers pre-check
            raise RuntimeError(
                f"query '{qr.query_id}': cannot key-shard after state "
                "materialized"
            )
        qr._keyshard = self
        qr._step = self._jit

    def init_state(self):
        """The unsharded init pytree with a leading [D] device axis — every
        device starts with an EMPTY group table; keys claim slots on their
        owner as they arrive (first-appearance allocation, per device)."""
        import jax
        import jax.numpy as jnp

        one = self.qr.init_state()
        return jax.tree_util.tree_map(
            lambda x: jnp.stack([jnp.asarray(x)] * self.n), one
        )

    # ---- device program --------------------------------------------------

    def _step_impl(self, state, tstates, batch, now):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from siddhi_tpu.core.event import EventBatch, KIND_CURRENT
        from siddhi_tpu.core.flow import Flow
        from siddhi_tpu.observability.lineage import LIN
        from siddhi_tpu.parallel.mesh import shard_map_unchecked

        qr = self.qr
        D = self.n

        def local(state_blk, b, t):
            st = jax.tree_util.tree_map(lambda l: l[0], state_blk)
            d = lax.axis_index(KEY_AXIS)
            flow = Flow(batch=b, ref=qr.ref, now=t, tables={})
            chain_state, flow = qr.chain.apply(st["chain"], flow)
            # the pre-mask flow batch == what the unsharded selector sees
            pre = flow.batch
            key = qr.selector.group.key_of(flow.env())
            mine = owner_of(key, D) == d
            # key-routed pre-pass: CURRENT/EXPIRED rows advance state only
            # on their owner; TIMER/RESET (and invalid) rows broadcast so
            # group eras advance in lockstep on every device
            keep = jnp.where(flow.sign != 0, mine, True)
            masked = EventBatch(pre.ts, pre.kind, pre.valid & keep, pre.cols)
            flow = dataclasses.replace(flow, batch=masked)
            sel_state, out = qr.selector.apply(st["sel"], flow)

            # ---- exact positional merge (the psum tree fold) ----
            # `mine` partitions EVERY row across the mesh, so the masked
            # psum reconstructs each lane's unsharded value exactly: the
            # owner computed it from the identical replicated inputs plus
            # the only aggregator lanes that row's group ever touches.
            merged_valid = lax.psum(out.valid.astype(jnp.int32), KEY_AXIS) > 0

            def merge_col(c):
                if jnp.issubdtype(c.dtype, jnp.floating):
                    # bitcast BEFORE masking: summing float identities
                    # would flip -0.0 to +0.0 and canonicalize NaNs
                    bits_dt = {2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}[
                        c.dtype.itemsize
                    ]
                    bits = lax.bitcast_convert_type(c, bits_dt)
                    summed = lax.psum(
                        jnp.where(mine, bits, jnp.zeros((), bits_dt)),
                        KEY_AXIS,
                    )
                    return lax.bitcast_convert_type(summed, c.dtype)
                if c.dtype == jnp.bool_:
                    return (
                        lax.psum(
                            jnp.where(mine, c, False).astype(jnp.int32),
                            KEY_AXIS,
                        )
                        > 0
                    )
                return lax.psum(
                    jnp.where(mine, c, jnp.zeros((), c.dtype)), KEY_AXIS
                )

            out2 = EventBatch(
                out.ts,
                out.kind,
                merged_valid,
                {nm: merge_col(c) for nm, c in out.cols.items()},
            )

            if qr.lineage is not None:
                # same lanes as QueryRuntime._step_impl, from the same
                # tensors: raw input, pre-mask chain output, merged out
                aux_d = flow.aux
                aux_d[LIN + "in"] = b.valid & (b.kind == KIND_CURRENT)
                aux_d[LIN + "in_ts"] = b.ts
                aux_d[LIN + "w_valid"] = pre.valid
                aux_d[LIN + "w_kind"] = pre.kind
                aux_d[LIN + "w_ts"] = pre.ts
                aux_d[LIN + "out_valid"] = out2.valid
                aux_d[LIN + "out_kind"] = out2.kind
                if "__group_key__" in out2.cols:
                    aux_d[LIN + "gkey"] = out2.cols["__group_key__"]

            aux_out = {}
            for k, v in flow.aux.items():
                if k.startswith(LIN):
                    aux_out[k] = v  # replicated provenance lanes
                elif k == "next_timer":
                    aux_out[k] = lax.pmin(jnp.min(jnp.asarray(v)), KEY_AXIS)
                else:
                    # host-warned flags stay SCALAR bools (_check_aux_flags)
                    aux_out[k] = (
                        lax.psum(
                            jnp.asarray(v).astype(jnp.int32).sum(), KEY_AXIS
                        )
                        > 0
                    )

            new_st = {"chain": chain_state, "sel": sel_state}
            return (
                jax.tree_util.tree_map(lambda l: l[None], new_st),
                out2,
                aux_out,
            )

        fn = shard_map_unchecked(
            local,
            self.mesh,
            (P(KEY_AXIS), P(), P()),
            (P(KEY_AXIS), P(), P()),
        )
        st2, out, aux = fn(state, batch, now)
        return st2, tstates, out, aux

    # ---- observability ---------------------------------------------------

    def describe_state(self) -> dict:
        """Per-device key occupancy and skew for /status.json, Prometheus
        (siddhi_keyshard_* families) and explain(). Device-derived fields
        are omitted on transfer-degraded backends (introspect contract)."""
        from siddhi_tpu.observability.introspect import device_reads_ok

        qr = self.qr
        g = qr.selector.group.capacity
        d: dict = {
            "query": qr.query_id,
            "devices": self.n,
            "axis": KEY_AXIS,
            "group_capacity": g,
        }
        if qr.state is None or not device_reads_ok():
            return d
        import jax

        with qr._receive_lock:
            n_dev = np.asarray(jax.device_get(qr.state["sel"]["group"]["n"]))
        keys = [int(x) for x in n_dev.reshape(-1)]
        total = sum(keys)
        d["per_device_keys"] = keys
        d["total_keys"] = total
        d["occupancy"] = [round(k / g, 4) for k in keys] if g else []
        mean = total / self.n if self.n else 0.0
        d["skew"] = round(max(keys) / mean, 3) if mean else 0.0
        return d

    # ---- snapshot SPI (core/persistence.py) ------------------------------

    def export_state(self, state):
        """Canonical single-device state tree for the snapshot: the [D, G]
        group tables collapse into one G-table (device-major slot order)
        and the [D, G]-leading aggregator lanes gather alongside. A
        restore re-hashes keys to owners, so the snapshot survives
        mesh-size changes (the rebalance path). Falls back to the raw
        sharded tree when the layout is not the canonical grouped shape."""
        import jax

        host = jax.tree_util.tree_map(
            lambda l: np.array(jax.device_get(l)), state
        )
        g = self.qr.selector.group.capacity
        sel = host.get("sel") if isinstance(host, dict) else None
        grp = sel.get("group") if isinstance(sel, dict) else None
        agg_leaves = (
            jax.tree_util.tree_leaves(sel.get("aggs"))
            if isinstance(sel, dict)
            else []
        )
        canonical = (
            grp is not None
            and isinstance(host, dict)
            and set(host) == {"chain", "sel"}
            and set(sel) <= {"aggs", "group"}
            and all(
                l.ndim >= 2 and l.shape[0] == self.n and l.shape[1] == g
                for l in agg_leaves
            )
        )
        if canonical:
            order = [
                (dd, s)
                for dd in range(self.n)
                for s in range(g)
                if grp["used"][dd, s]
            ]
            canonical = len(order) <= g
        if not canonical:
            return {"__keyshard_raw__": self.n, "state": host}

        one = jax.tree_util.tree_map(
            lambda l: np.array(jax.device_get(l)), self.qr.init_state()
        )
        pg = one["sel"]["group"]
        for i, (dd, s) in enumerate(order):
            pg["keys"][i] = grp["keys"][dd, s]
            pg["used"][i] = True
        pg["n"] = np.int32(len(order)).reshape(())

        def gather(dst, src):
            dst = np.array(dst)
            for i, (dd, s) in enumerate(order):
                dst[i] = src[dd, s]
            return dst

        one["sel"]["aggs"] = jax.tree_util.tree_map(
            gather, one["sel"]["aggs"], sel["aggs"]
        )
        return one

    def import_state(self, value):
        """Rebuild the [D]-sharded state from a canonical (or raw) snapshot
        tree, re-hashing every group key to its owner on THIS mesh."""
        import jax
        import jax.numpy as jnp

        if isinstance(value, dict) and "__keyshard_raw__" in value:
            snap_d = int(value["__keyshard_raw__"])
            if snap_d != self.n:
                raise ValueError(
                    f"query '{self.qr.query_id}': raw key-sharded snapshot "
                    f"taken on {snap_d} devices cannot restore onto "
                    f"{self.n} (canonical export required for rebalance)"
                )
            return jax.tree_util.tree_map(jnp.asarray, value["state"])

        host = jax.tree_util.tree_map(
            lambda l: np.array(jax.device_get(l)), value
        )
        g = self.qr.selector.group.capacity
        grp = host["sel"]["group"]
        ns = jax.tree_util.tree_map(
            lambda l: np.array(jax.device_get(l)), self.init_state()
        )
        ng = ns["sel"]["group"]
        owners = owner_of(np.asarray(grp["keys"], np.int64), self.n)
        counts = [0] * self.n
        place: dict = {}  # canonical slot -> (device, local slot)
        for s in range(g):
            if not grp["used"][s]:
                continue
            dd = int(owners[s])
            i = counts[dd]
            counts[dd] += 1
            ng["keys"][dd, i] = grp["keys"][s]
            ng["used"][dd, i] = True
            place[s] = (dd, i)
        ng["n"] = np.asarray(counts, np.int32)

        def scatter(dst, src):
            for s, (dd, i) in place.items():
                dst[dd, i] = src[s]
            return dst

        ns["sel"]["aggs"] = jax.tree_util.tree_map(
            scatter, ns["sel"]["aggs"], host["sel"]["aggs"]
        )
        return jax.tree_util.tree_map(jnp.asarray, ns)


# ---------------------------------------------------------------------------
# placement (called by ShardRuntime when axis == 'keys')
# ---------------------------------------------------------------------------


def apply_keyshard(app_runtime, devices) -> dict:
    """Arm key-sharded execution on every eligible grouped query. Returns
    qid -> placement info for /status.json and explain(); ineligible
    GROUPED queries get a {"sharded": False, "reason"} entry so the veto
    is observable (SA124-style). Idempotent: already-armed queries (churn
    re-arms) are left with their live [D] state."""
    from siddhi_tpu.core.query_runtime import QueryRuntime

    fused_members = set()
    for j in app_runtime.junctions.values():
        fi = getattr(j, "fused_ingest", None)
        if fi is not None:
            for ep in getattr(fi, "endpoints", ()):
                fused_members.add(id(ep.qr))

    placed: dict = {}
    for qid, qr in list(app_runtime.queries.items()):
        if getattr(qr, "_keyshard", None) is not None:
            placed[qid] = {
                "sharded": True,
                "devices": qr._keyshard.n,
                "axis": KEY_AXIS,
                "group_capacity": qr.selector.group.capacity,
            }
            continue
        ok, why = keyed_shardable(qr)
        grouped = (
            type(qr) is QueryRuntime
            and getattr(qr.selector, "group", None) is not None
        )
        if ok and id(qr) in fused_members:
            # belt-and-braces: the planner's H_KEYSHARD hazard and the
            # runtime _wire_fuse_candidate veto keep eligible queries out
            # of fused groups; if one slipped in, fused dispatch would
            # bypass the sharded step entirely — refuse, loudly
            ok, why = False, "member of a fused ingest group"
            log.warning(
                "query '%s': keyed sharding skipped — %s (fusion veto "
                "missed; report this)", qid, why,
            )
        if not ok:
            if grouped:
                placed[qid] = {"sharded": False, "reason": why}
            continue
        if qr.state is not None:
            placed[qid] = {
                "sharded": False,
                "reason": "state already materialized",
            }
            continue
        ex = KeyShardedGroupExec(qr, devices)
        ex.arm()
        placed[qid] = {
            "sharded": True,
            "devices": ex.n,
            "axis": KEY_AXIS,
            "group_capacity": qr.selector.group.capacity,
        }
        sm = app_runtime.statistics_manager
        if sm is not None:
            sm.register_shard(f"query.{qid}", ex)
        log.info(
            "query '%s': group-by state key-sharded across %d devices",
            qid, ex.n,
        )
    return placed


def apply_join_mesh(app_runtime, devices) -> dict:
    """Place join window state across the mesh: every join-side state leaf
    whose leading (ring) axis divides the device count is sharded on
    P('keys'); the sides' jitted steps are re-jitted with explicit in/out
    shardings. The traced program is UNCHANGED — GSPMD realizes the probe
    as a cross-device gather — so emissions and `view_seq()` lineage stay
    byte-identical. Returns qid -> placement info."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from siddhi_tpu.core.join import JoinQueryRuntime

    D = len(devices)
    placed: dict = {}
    mesh = None
    for qid, qr in list(app_runtime.queries.items()):
        if type(qr) is not JoinQueryRuntime:
            continue
        if getattr(qr, "_joinshard", False):
            placed[qid] = {"sharded": True, "devices": D, "axis": KEY_AXIS}
            continue
        spec = jax.eval_shape(qr.init_state)

        def eligible(l):
            return l.ndim >= 1 and l.shape[0] >= D and l.shape[0] % D == 0

        n_sharded = sum(
            1 for l in jax.tree_util.tree_leaves(spec["join"]) if eligible(l)
        )
        if n_sharded == 0:
            placed[qid] = {
                "sharded": False,
                "reason": f"no join-state axis divisible by {D} devices",
            }
            continue
        if qr.state is not None:
            placed[qid] = {
                "sharded": False,
                "reason": "state already materialized",
            }
            continue
        if mesh is None:
            mesh = Mesh(np.array(devices), (KEY_AXIS,))
        shard = NamedSharding(mesh, P(KEY_AXIS))
        repl = NamedSharding(mesh, P())
        state_sh = {
            "join": jax.tree_util.tree_map(
                lambda l: shard if eligible(l) else repl, spec["join"]
            ),
            "sel": repl,
        }
        qr._steps = {
            side: jax.jit(
                lambda st, ts, b, now, _s=side: qr._step_impl(
                    st, ts, b, now, _s
                ),
                in_shardings=(state_sh, repl, repl, repl),
                out_shardings=(state_sh, repl, repl, repl),
                donate_argnums=(0,),
            )
            for side in ("l", "r")
        }
        qr._joinshard = True
        placed[qid] = {
            "sharded": True,
            "devices": D,
            "axis": KEY_AXIS,
            "sharded_leaves": n_sharded,
        }
        log.info(
            "query '%s': join window state sharded across %d devices "
            "(%d leaves)", qid, D, n_sharded,
        )
    return placed
