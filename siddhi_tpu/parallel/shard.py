"""First-class sharded execution: `@app:shard(devices='N', axis=...)`.

The multichip dryrun (`__graft_entry__.py` + `parallel/mesh.py`) proved the
hard part — an 8-device mesh with the partition axis sharded and the batch
axis key-routed per device, checksum-identical to unsharded execution — but
none of it was reachable from a real app. This module promotes that contract
to an engine runtime mode, resolved at `start()`:

* **axis='part'** — every `PartitionedQueryRuntime`'s existing leading `[P]`
  state axis is placed on a `jax.sharding.Mesh` over the first N devices:
  windows/aggregators of different partition keys advance in parallel on
  different chips, with XLA inserting the cross-device collectives (the
  psum/min aux reduction, the output gather at decode). The input batch is
  REPLICATED to every device — emission order is part of the engine contract,
  and the dryrun's key-routed batch pre-pass compacts each device's
  sub-batch, which reorders emissions ACROSS partition slots within a batch
  (set-identical, order-different). The routed variant stays available as
  `mesh.shard_partitioned_query(routed=True)` for checksum workloads.

* **axis='batch'** — junctions whose fused endpoints are all STATELESS
  (filter / projection / stream-function chains: no window, no aggregator,
  no group-by, no table, no rate limiter) get a `BatchShardRouter`:
  each `send_columns` call's micro-batches are round-robin-routed
  (micro-batch k -> device k % D) into per-device wire chunks, dispatched
  as per-device chunk programs, and the packed outputs are merged back in
  ORIGINAL batch order before callback delivery — byte-identical to the
  unsharded path, because a stateless chain's output for a micro-batch
  depends only on that micro-batch. Stateful non-partitioned queries keep
  the single-device fused path (key-routed sharding for those is the
  partition construct: `partition with (key of S)` + axis='part').

* **axis='auto'** (default) applies both.

`SIDDHI_TPU_SHARD=N` overrides the annotation process-wide (0 forces off) —
the verify-parity CI leg runs the whole suite under `SIDDHI_TPU_SHARD=8`
with `XLA_FLAGS=--xla_force_host_platform_device_count=8` and diffs every
case's rows against the unsharded run.

Validation is ONE rule set (`iter_shard_annotation_problems`) shared by the
runtime resolver (raises at app creation) and the analyzer's SA129
diagnostic, like SA125–SA128.

Grounding: the cloud-native pattern-detection framework shards detection by
key exactly this way (PAPERS.md, arxiv 2401.09960); "To Share, or not to
Share" (arxiv 2101.00361) motivates keeping shared state local to a shard —
here each device owns its partition slots' windows outright.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

SHARD_ENV = "SIDDHI_TPU_SHARD"
SHARD_AXIS_ENV = "SIDDHI_TPU_SHARD_AXIS"
MAX_DEVICES = 64
_AXES = ("auto", "part", "batch", "keys")


# ---------------------------------------------------------------------------
# annotation / env resolution (one rule set for runtime + analyzer SA129)
# ---------------------------------------------------------------------------


def shard_env_override() -> Optional[int]:
    """Process-wide device-count override: N (force N-device sharding),
    0 (force off), or None (defer to the app's @app:shard annotation)."""
    v = os.environ.get(SHARD_ENV, "").strip().lower()
    if not v:
        return None
    if v in ("off", "false", "no"):
        return 0
    try:
        return max(0, int(v))
    except ValueError:
        log.warning("ignoring malformed %s=%r", SHARD_ENV, v)
        return None


def shard_axis_override() -> Optional[str]:
    """Process-wide axis override (SIDDHI_TPU_SHARD_AXIS): one of the
    `_AXES` names, or None to defer to the app's @app:shard annotation.
    Lets CI drive the same app through every placement strategy."""
    v = os.environ.get(SHARD_AXIS_ENV, "").strip().lower()
    if not v:
        return None
    if v not in _AXES:
        log.warning(
            "ignoring malformed %s=%r (expected one of %s)",
            SHARD_AXIS_ENV, v, ", ".join(_AXES),
        )
        return None
    return v


def iter_shard_annotation_problems(ann):
    """Yield one message per malformed `@app:shard` element — THE validation
    rules, shared by the runtime resolver (raises on the first) and the
    analyzer's SA129 diagnostics (reports them all), so the two can never
    drift. Accepted shapes:
    @app:shard(devices='N'[, axis='part|batch|keys|auto'])
    or the sole-positional @app:shard('N')."""
    sole_positional = len(ann.elements) == 1 and ann.elements[0][0] is None
    for k, v in ann.elements:
        if k == "devices" or (k is None and sole_positional):
            try:
                ok = 1 <= int(v) <= MAX_DEVICES
            except (TypeError, ValueError):
                ok = False
            if not ok:
                yield (
                    f"@app:shard devices '{v}' must be an integer in "
                    f"1..{MAX_DEVICES}"
                )
        elif k == "axis":
            if str(v).strip().lower() not in _AXES:
                yield (
                    f"@app:shard axis '{v}' must be one of "
                    f"{', '.join(_AXES)}"
                )
        else:
            yield (
                f"unknown @app:shard option '{k if k is not None else v}' "
                "(expected devices, axis)"
            )


def resolve_shard_annotation(ann) -> tuple[int, str]:
    """(requested_devices, axis) for one app from its `@app:shard`
    annotation (or None) plus the SIDDHI_TPU_SHARD env override (which wins,
    in both directions). requested_devices == 0 means sharding is off.
    Raises SiddhiAppCreationError on malformed options — the runtime analog
    of the analyzer's SA129 diagnostic."""
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    devices = 0
    axis = "auto"
    if ann is not None:
        for problem in iter_shard_annotation_problems(ann):
            raise SiddhiAppCreationError(problem)
        v = ann.element("devices")
        if v is None and len(ann.elements) == 1 and ann.elements[0][0] is None:
            v = ann.elements[0][1]  # strict sole-positional fallback
        devices = int(v) if v is not None else 0
        ax = ann.element("axis")
        if ax is not None:
            axis = str(ax).strip().lower()
    env = shard_env_override()
    if env is not None:
        devices = env
    env_axis = shard_axis_override()
    if env_axis is not None:
        axis = env_axis
    return devices, axis


# ---------------------------------------------------------------------------
# batch-axis router eligibility
# ---------------------------------------------------------------------------


def shardable_stateless(qr) -> bool:
    """True when a fused endpoint's query carries NO cross-batch state, so
    its output for a micro-batch depends only on that micro-batch and
    micro-batches can be routed to different devices and merged back in
    batch order with byte-identical results. The contract lives on
    `QueryRuntime.stateless_chain`; anything else (patterns, joins,
    partitioned runtimes — all stateful) is never shardable this way."""
    from siddhi_tpu.core.query_runtime import QueryRuntime

    return type(qr) is QueryRuntime and qr.stateless_chain


def router_eligible(fi) -> bool:
    """May a junction's fused ingest engine be batch-axis sharded? Every
    endpoint must be provably stateless, and there must be no residual
    per-batch consumers and no cross-query shared rings (both exist only
    for stateful chains anyway)."""
    if fi.residual or fi.share_sets:
        return False
    if not fi.endpoints:
        return False
    return all(shardable_stateless(ep.qr) for ep in fi.endpoints)


# ---------------------------------------------------------------------------
# batch-axis round-robin router
# ---------------------------------------------------------------------------


class BatchShardRouter:
    """Round-robin batch-axis data parallelism for one junction's fused
    ingest: micro-batch k of a columnar send routes to device k % D, each
    device's batches are encoded into per-device wire chunks (one fresh
    buffer per chunk — see `_send` on why in-flight chunks must not share
    pooled slots) shipped through the SAME jitted chunk program (jax
    compiles one executable per device), and the packed outputs merge back
    in ORIGINAL batch order before delivery.

    Armed only on junctions whose endpoints are all stateless
    (`router_eligible`), so per-device execution order cannot change any
    result. Per-device dispatch/event counters feed `/status.json`,
    `/profile`, explain(), and the Prometheus shard gauges."""

    def __init__(self, junction, devices):
        self.junction = junction
        self.devices = list(devices)
        self.dispatches = [0] * len(self.devices)
        self.events = [0] * len(self.devices)
        self.sends = 0
        self._lock = threading.Lock()
        # senders serialize on _send_gate (the counters and the merge drain
        # assume one producer); a callback that re-enters send_columns from
        # inside the merged drain falls back to the single-device path
        # instead of deadlocking on its own gate
        self._send_gate = threading.Lock()
        self._sender = None

    # ---- observability ---------------------------------------------------

    def describe_state(self) -> dict:
        total = max(1, sum(self.events))
        d = len(self.devices)
        return {
            "devices": d,
            "sends": self.sends,
            "per_device_dispatches": list(self.dispatches),
            "per_device_events": list(self.events),
            # occupancy: each device's event share normalized so 1.0 means a
            # perfectly even split across the D devices
            "occupancy": [round(e * d / total, 3) for e in self.events],
        }

    # ---- send ------------------------------------------------------------

    def try_send(
        self, fi, prog, encode, deliver, ts_arr, cols, n: int, B: int, now,
        ds, tracked, tr, stream_span,
    ) -> Optional[bool]:
        """Sharded fused send of one columnar call. Returns None when the
        call should fall back to the single-device fused path (too few
        micro-batches for >= 2 devices, or a narrow-wire misfit before
        anything was dispatched), True once the sharded send committed."""
        M = -(-n // B)  # micro-batches in this call
        D = min(len(self.devices), M)
        if D < 2:
            return None
        if self._sender is threading.current_thread():
            return None  # re-entrant send from a drain callback
        with self._send_gate:
            self._sender = threading.current_thread()
            try:
                return self._send(
                    fi, prog, encode, deliver, ts_arr, cols, n, B, now,
                    ds, tracked, tr, stream_span, M, D,
                )
            finally:
                self._sender = None

    def _send(
        self, fi, prog, encode, deliver, ts_arr, cols, n: int, B: int, now,
        ds, tracked, tr, stream_span, M: int, D: int,
    ) -> Optional[bool]:
        from siddhi_tpu.core.event import WireNarrowMisfit

        # round-robin assignment: micro-batch k -> device k % D, kept in
        # per-device order so each device's chunk iterations align with its
        # assigned global batches
        assigned = [list(range(d, M, D)) for d in range(D)]

        # encode EVERY device's chunks first (pure host work), each into a
        # FRESH wire buffer: a narrow-wire misfit here falls back to the
        # unsharded path with NOTHING dispatched (which owns the full-width
        # rebuild), and a fresh buffer per in-flight chunk means no reuse
        # gate is needed at all — a pooled slot would be re-acquired before
        # its first occupant shipped, overwriting staged bytes (the
        # single-device pipeline can pool because it ships each slot before
        # acquiring the next)
        staged: list[list] = []
        try:
            for d in range(D):
                idxs = assigned[d]
                chunks = []
                for ofs in range(0, len(idxs), fi.K):
                    part = idxs[ofs : ofs + fi.K]
                    K = fi._chunk_K(len(part))
                    wire = np.zeros((K, fi._wire_bytes), dtype=np.uint8)
                    counts = np.zeros((K,), dtype=np.int32)
                    bases = np.zeros((K,), dtype=np.int64)
                    for j, k in enumerate(part):
                        lo = k * B
                        hi = min(lo + B, n)
                        counts[j] = hi - lo
                        buf, base = encode(
                            ts_arr[lo:hi],
                            {kk: v[lo:hi] for kk, v in cols.items()},
                            hi - lo,
                        )
                        bases[j] = base
                        wire[j, :] = buf
                    chunks.append((wire, counts, bases, len(part), part))
                staged.append(chunks)
        except WireNarrowMisfit:
            return None

        # dispatch round-robin across devices so all D run concurrently
        # (jax dispatch is async; each chunk's submit returns immediately)
        import jax

        results: list[list] = [[] for _ in range(D)]
        rounds = max(len(c) for c in staged)
        # lineage: chunks dispatch round-robin (NOT global batch order), so
        # observations park keyed by global batch index and replay in order
        # at _lin_end_send (observability/lineage.py)
        fi._lin_begin_send()
        try:
            for r in range(rounds):
                for d in range(D):
                    if r >= len(staged[d]):
                        continue
                    wire, counts, bases, nb, part = staged[d][r]
                    dev_wire = jax.device_put(wire, self.devices[d])
                    packs, completion = fi._dispatch_chunk(
                        prog, dev_wire, counts, bases, now, ds, tracked, tr,
                        stream_span, deliver=deliver, lin_ks=part,
                    )
                    if packs is None and completion is None:
                        # guarded dispatch failure: the junction's policy
                        # owned it; this chunk's batches deliver nothing
                        # (the exact per-batch-path semantics of a dropped
                        # failing batch)
                        results[d].append((None, counts, nb))
                        continue
                    with self._lock:
                        self.dispatches[d] += 1
                        self.events[d] += int(counts.sum())
                    results[d].append((packs, counts, nb))
        finally:
            # even when an unguarded dispatch failure propagates to the
            # sender, the already-dispatched chunks' parked observations
            # must replay — dropping them would desync every recorder's
            # seq accounting for all later sends
            fi._lin_end_send()
        with self._lock:
            self.sends += 1
        if deliver:
            # same failure contract as every single-device drain
            # (_drain_guarded): a guarded junction's machinery owns callback
            # errors, an unguarded one re-raises to the sender
            try:
                self._merged_drain(fi, results, M, D)
            except Exception as e:
                j = self.junction
                if j.exception_handler is None and j.fault_policy is None:
                    raise
                j._on_worker_error(e, "sharded drain")
        return True

    # ---- ordered merge drain --------------------------------------------

    def _merged_drain(self, fi, results, M: int, D: int) -> None:
        """Read back every device's packed outputs and deliver each
        endpoint's rows in ORIGINAL micro-batch order: global batch k's
        segment comes from device k % D's next undelivered iteration, so
        the interleaved row stream (and the per-segment callback grouping)
        is byte-identical to the single-device drain."""
        import jax

        for pos, i in enumerate(fi._deliver_idx):
            qr = fi.endpoints[i].qr
            if not getattr(qr, "query_callbacks", None):
                continue
            _layout, row_bytes = fi._deliver_layout[i]
            dev_rows: list[np.ndarray] = []
            dev_cnts: list[np.ndarray] = []
            for d in range(D):
                parts: list[np.ndarray] = []
                cnt_parts: list[np.ndarray] = []
                for packs, counts, nb in results[d]:
                    K = counts.shape[0]
                    if packs is None:  # dropped chunk: zero rows, kept
                        cnt_parts.append(np.zeros((nb,), np.int32))
                        continue  # alignment with its assigned batches
                    hdr_rows = -(-4 * K // row_bytes)
                    # header first, then exactly the filled row prefix —
                    # never the whole [K*cap] buffer
                    hdr = np.ascontiguousarray(
                        jax.device_get(packs[pos]["buf"][:hdr_rows])
                    )
                    cnts = hdr.reshape(-1)[: 4 * K].view(np.int32)
                    total = int(cnts.sum())
                    if total:
                        parts.append(np.ascontiguousarray(
                            jax.device_get(
                                packs[pos]["buf"][
                                    hdr_rows : hdr_rows + total
                                ]
                            )
                        ))
                    # padding iterations (j >= nb) carry count 0 and no rows
                    cnt_parts.append(np.asarray(cnts[:nb], np.int32))
                dev_rows.append(
                    np.concatenate(parts)
                    if parts
                    else np.zeros((0, row_bytes), np.uint8)
                )
                dev_cnts.append(
                    np.concatenate(cnt_parts)
                    if cnt_parts
                    else np.zeros((0,), np.int32)
                )
            seq_parts: list[np.ndarray] = []
            cseq = np.zeros((M,), dtype=np.int32)
            offs = [0] * D
            iters = [0] * D
            for k in range(M):
                d = k % D
                ci = iters[d]
                iters[d] += 1
                c = int(dev_cnts[d][ci]) if ci < len(dev_cnts[d]) else 0
                cseq[k] = c
                if c:
                    seq_parts.append(dev_rows[d][offs[d] : offs[d] + c])
                    offs[d] += c
            total = int(cseq.sum())
            if not total:
                continue
            host = np.concatenate(seq_parts)
            fi.deliver_endpoint(i, host, cseq, total)


# ---------------------------------------------------------------------------
# partition-axis mesh placement
# ---------------------------------------------------------------------------


def apply_partition_mesh(app_runtime, devices) -> dict:
    """Place every plain `PartitionedQueryRuntime`'s `[P]` state axis on a
    mesh over `devices`, swapping the runtime's outer jitted step for one
    with explicit in/out shardings (the replicated-batch mode: each device
    advances only its own partition slots; emission positions — and so
    delivery order — are bit-identical to the unsharded vmap). Returns
    qid -> placement info for `/status.json` and explain()."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from siddhi_tpu.core.partition import PartitionedQueryRuntime

    D = len(devices)
    placed: dict = {}
    mesh = None
    for pr in app_runtime.partitions:
        for qr in pr.queries:
            if type(qr) is not PartitionedQueryRuntime or qr.key_of is None:
                # joins/patterns/#inner-fed queries keep the single-device
                # vmapped step (their [P] axes are shardable the same way;
                # scoped out until the mesh contract covers their timers)
                continue
            qid = qr.query_id
            padded = 0
            if qr.p % D != 0:
                if qr.state is not None:
                    # live [P] buffers can't be resized in place; only a
                    # pre-first-event placement pads
                    placed[qid] = {
                        "sharded": False,
                        "reason": (
                            f"partitionCapacity {qr.p} % devices {D} != 0 "
                            "with live state"
                        ),
                    }
                    continue
                # pad the [P] axis to the next multiple of D with DEAD
                # slots: the shared ptable keeps its original capacity so
                # key->slot allocation (and its overflow threshold) is
                # untouched, and the padded lanes behave exactly like
                # never-allocated lanes — timer rows run on fresh init
                # state and emit nothing, so emissions stay byte-identical
                target = -(-qr.p // D) * D
                padded = target - qr.p
                log.info(
                    "query '%s': padding @app:partitionCapacity %d to %d "
                    "(%d dead slot(s)) for the %d-device mesh",
                    qid, qr.p, target, padded, D,
                )
                qr.p = target
            if mesh is None:
                mesh = Mesh(np.array(devices), ("part",))
            shard = NamedSharding(mesh, P("part"))
            repl = NamedSharding(mesh, P())
            # same computation as the unsharded _pstep_outer (identical
            # emission lanes), state resharded [P] across the mesh; the aux
            # any()/min() reductions become XLA cross-device collectives and
            # the output decode gathers — the cross-device merge step.
            # donate_argnums matches the unsharded jit: the [P] state is the
            # largest tensor set in the system and must update in place
            # (the first call's host-built state isn't donatable — one
            # ignorable warning — every later call donates sharded buffers)
            qr._pstep_outer = jax.jit(
                qr._pstep_outer_impl,
                in_shardings=(repl, shard, repl, repl),
                out_shardings=(repl, shard, shard, repl),
                donate_argnums=(1,),
            )
            placed[qid] = {
                "sharded": True,
                "devices": D,
                "axis": "part",
                "local_slots": qr.p // D,
            }
            if padded:
                placed[qid]["padded_slots"] = padded
    return placed


# ---------------------------------------------------------------------------
# the app-level shard runtime (built at start())
# ---------------------------------------------------------------------------


class ShardRuntime:
    """Resolved sharded-execution mode of one app. Built by
    `SiddhiAppRuntime.start()` from the creation-time `@app:shard` /
    SIDDHI_TPU_SHARD resolution; `apply()` places partitioned state on the
    mesh and arms batch routers on eligible junctions."""

    def __init__(self, app_runtime, requested: int, axis: str):
        import jax

        self.app = app_runtime
        self.axis = axis
        self.requested = int(requested)
        devs = jax.devices()
        n = min(self.requested, len(devs))
        if n < self.requested:
            log.warning(
                "app '%s': @app:shard requested %d devices but only %d are "
                "visible; clamping (set XLA_FLAGS="
                "--xla_force_host_platform_device_count=N for a virtual "
                "CPU mesh)",
                app_runtime.name, self.requested, len(devs),
            )
        self.devices = devs[:n]
        self.partitioned: dict = {}
        self.routers: dict = {}
        self.keyshard: dict = {}
        self.joins: dict = {}

    @property
    def n(self) -> int:
        return len(self.devices)

    def apply(self) -> None:
        if self.n < 2:
            log.warning(
                "app '%s': sharded execution disabled (%d device(s) "
                "available)", self.app.name, self.n,
            )
            return
        if self.axis in ("auto", "part", "keys"):
            self.partitioned = apply_partition_mesh(self.app, self.devices)
        self.rearm_keyshard()
        self.rearm_routers()

    def rearm_keyshard(self) -> None:
        """(Re)arm key-sharded group-by and join state (axis='keys' only —
        parallel/keyshard.py). Called by apply() at start AND by the churn
        splice after fused engines are rebuilt: a hot-deployed grouped
        query (state still None) gets armed before its first event;
        already-armed queries keep their live [D] state and jitted step."""
        if self.n < 2 or self.axis != "keys":
            return
        from siddhi_tpu.parallel.keyshard import (
            apply_join_mesh,
            apply_keyshard,
        )

        self.keyshard.update(apply_keyshard(self.app, self.devices))
        self.joins.update(apply_join_mesh(self.app, self.devices))

    def rearm_routers(self) -> None:
        """(Re)arm batch-axis routers on every eligible fused ingest
        engine. Called by apply() at start AND by the churn splice
        (core/churn.py) after fused engines are rebuilt: a hot
        deploy/undeploy can change a junction's eligibility (a stateful
        query joining the group vetoes the router; its removal restores
        it), and the rebuilt engines start with `shard_router = None`."""
        if self.n < 2 or self.axis not in ("auto", "batch"):
            return
        sm = self.app.statistics_manager
        prev_routers = self.routers
        self.routers = {}
        for sid, j in list(self.app.junctions.items()):
            fi = j.fused_ingest
            if fi is None or not router_eligible(fi):
                continue
            r = BatchShardRouter(j, self.devices)
            prev = prev_routers.get(sid)
            if prev is not None and len(prev.devices) == len(self.devices):
                # carry the cumulative counters into the replacement: the
                # siddhi_shard_device_*_total families are Prometheus
                # COUNTERS — zeroing them on every churn splice would read
                # as counter resets in rate()/increase() and break the
                # per-device-sums == everything-sent invariant
                r.dispatches = list(prev.dispatches)
                r.events = list(prev.events)
                r.sends = prev.sends
            fi.shard_router = r
            self.routers[sid] = r
            if sm is not None:
                sm.register_shard(f"stream.{sid}", r)

    def describe_state(self) -> dict:
        d: dict = {
            "devices": self.n,
            "requested": self.requested,
            "axis": self.axis,
        }
        if self.partitioned:
            d["partitioned"] = dict(self.partitioned)
        if self.routers:
            d["streams"] = {
                sid: r.describe_state() for sid, r in self.routers.items()
            }
        if self.keyshard:
            ks = {}
            for qid, info in self.keyshard.items():
                qr = self.app.queries.get(qid)
                ex = getattr(qr, "_keyshard", None)
                live = ex.describe_state() if ex is not None else {}
                ks[qid] = {**info, **live}
            d["keyshard"] = ks
        if self.joins:
            d["joins"] = dict(self.joins)
        return d
