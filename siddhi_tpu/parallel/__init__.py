"""Multi-device execution: mesh sharding for partitioned state
(parallel/mesh.py, the dryrun-proven routed step) and the first-class
`@app:shard` runtime mode (parallel/shard.py)."""

from siddhi_tpu.parallel.shard import (  # noqa: F401
    BatchShardRouter,
    ShardRuntime,
    resolve_shard_annotation,
    shard_env_override,
)
