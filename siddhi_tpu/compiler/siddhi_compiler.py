"""Static SiddhiQL parse entry points.

Reference: siddhi-query-compiler .../SiddhiCompiler.java:57-192 — one entry per
construct (app, query, store query, expression, time constant, definitions).
"""

from __future__ import annotations

from siddhi_tpu.compiler.parser import Parser
from siddhi_tpu.query_api.execution import Query, StoreQuery
from siddhi_tpu.query_api.expression import Expression
from siddhi_tpu.query_api.siddhi_app import SiddhiApp


class SiddhiCompiler:
    @staticmethod
    def parse(source: str) -> SiddhiApp:
        return Parser(source).parse_app()

    @staticmethod
    def parse_query(source: str) -> Query:
        p = Parser(source)
        anns = p._annotations()
        q = p._query(anns)
        p.accept(";")
        p.expect("EOF")
        return q

    @staticmethod
    def parse_store_query(source: str) -> StoreQuery:
        return Parser(source).parse_store_query()

    @staticmethod
    def parse_expression(source: str) -> Expression:
        p = Parser(source)
        e = p._expression()
        p.expect("EOF")
        return e

    @staticmethod
    def parse_time_constant(source: str) -> int:
        p = Parser(source)
        ms = p._time_value()
        p.expect("EOF")
        return ms
