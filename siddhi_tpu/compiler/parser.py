"""SiddhiQL recursive-descent parser: token stream -> query-api AST.

Covers the reference grammar's surface (reference:
siddhi-query-compiler .../SiddhiQL.g4 + internal/SiddhiQLBaseVisitorImpl.java):
app/definition/query/partition/store-query forms, annotations, joins, pattern and
sequence chains (every / count <m:n> / * + ? / logical and-or / absent not-for),
selectors with group by / having / order by / limit / offset, output rates, and
the full expression grammar with reference operator precedence
(not > */% > +- > relational > equality > in > and > or).
"""

from __future__ import annotations

from typing import Optional

from siddhi_tpu.compiler.tokenizer import TIME_UNITS, Token, tokenize
from siddhi_tpu.core.errors import SiddhiParserError
from siddhi_tpu.core.types import AttrType
from siddhi_tpu.query_api.annotation import Annotation
from siddhi_tpu.query_api.definition import (
    AggregationDefinition,
    Attribute,
    Duration,
    FunctionDefinition,
    StreamDefinition,
    TableDefinition,
    TimePeriod,
    TriggerDefinition,
    WindowDefinition,
    WindowSpec,
)
from siddhi_tpu.query_api.execution import (
    AbsentStreamStateElement,
    CountStateElement,
    DeleteStream,
    EventOutputRate,
    EveryStateElement,
    Filter,
    InputStore,
    InsertIntoStream,
    JoinEventTrigger,
    JoinInputStream,
    JoinType,
    LogicalStateElement,
    LogicalType,
    NextStateElement,
    OrderByAttribute,
    OrderDir,
    OutputAttribute,
    OutputEventsFor,
    OutputRateType,
    Partition,
    Query,
    RangePartitionProperty,
    RangePartitionType,
    ReturnStream,
    Selector,
    SingleInputStream,
    SnapshotOutputRate,
    StateElement,
    StateInputStream,
    StateStreamType,
    StoreQuery,
    StreamFunctionHandler,
    StreamStateElement,
    TimeOutputRate,
    UpdateOrInsertStream,
    UpdateSetAttribute,
    UpdateStream,
    ValuePartitionType,
    WindowHandler,
)
from siddhi_tpu.query_api.expression import (
    Add,
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    Divide,
    Expression,
    In,
    IsNull,
    Mod,
    Multiply,
    Not,
    Or,
    Subtract,
    TimeConstant,
    Variable,
)
from siddhi_tpu.query_api.siddhi_app import SiddhiApp

_TYPES = {
    "string": AttrType.STRING,
    "int": AttrType.INT,
    "long": AttrType.LONG,
    "float": AttrType.FLOAT,
    "double": AttrType.DOUBLE,
    "bool": AttrType.BOOL,
    "object": AttrType.OBJECT,
}

_DURATIONS = {
    "sec": Duration.SECONDS, "seconds": Duration.SECONDS, "second": Duration.SECONDS,
    "min": Duration.MINUTES, "minutes": Duration.MINUTES, "minute": Duration.MINUTES,
    "hour": Duration.HOURS, "hours": Duration.HOURS,
    "day": Duration.DAYS, "days": Duration.DAYS,
    "month": Duration.MONTHS, "months": Duration.MONTHS,
    "year": Duration.YEARS, "years": Duration.YEARS,
}

# keywords that terminate an attribute/expression list in a selector
_SECTION_KW = {
    "group", "having", "order", "limit", "offset", "output", "insert",
    "delete", "update", "return",
}


class Parser:
    def __init__(self, src: str):
        self.toks = tokenize(src)
        self.pos = 0

    # ---- token helpers ---------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.pos + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.pos]
        if t.type != "EOF":
            self.pos += 1
        return t

    def at(self, type_: str) -> bool:
        return self.peek().type == type_

    def at_kw(self, *kws: str, ahead: int = 0) -> bool:
        t = self.peek(ahead)
        return t.type == "ID" and t.text.lower() in kws

    def accept(self, type_: str) -> Optional[Token]:
        if self.at(type_):
            return self.next()
        return None

    def accept_kw(self, *kws: str) -> Optional[Token]:
        if self.at_kw(*kws):
            return self.next()
        return None

    def expect(self, type_: str) -> Token:
        if not self.at(type_):
            t = self.peek()
            raise self.err(f"expected {type_!r}, found {t.text!r}")
        return self.next()

    def expect_kw(self, *kws: str) -> Token:
        if not self.at_kw(*kws):
            t = self.peek()
            raise self.err(f"expected {'/'.join(kws)!r}, found {t.text!r}")
        return self.next()

    def err(self, msg: str) -> SiddhiParserError:
        t = self.peek()
        return SiddhiParserError(msg, t.line, t.col)

    def stamp(self, node, tok: Token):
        """Thread the source position of `tok` onto an AST node (only when the
        node does not already carry a more specific position)."""
        if getattr(node, "line", None) is None:
            node.line, node.col = tok.line, tok.col
        return node

    def name(self) -> str:
        t = self.peek()
        if t.type in ("ID", "QID"):
            self.next()
            return t.text
        raise self.err(f"expected identifier, found {t.text!r}")

    # ---- app -------------------------------------------------------------

    def parse_app(self) -> SiddhiApp:
        app = SiddhiApp()
        while self.at("@") and self._is_app_annotation():
            app.annotations.append(self._app_annotation())
        while True:
            while self.accept(";"):
                pass
            if self.at("EOF"):
                break
            anns = self._annotations()
            if self.at_kw("define"):
                self._definition(app, anns)
            elif self.at_kw("partition"):
                app.add_partition(self._partition(anns))
            elif self.at_kw("from"):
                app.add_query(self._query(anns))
            else:
                raise self.err(f"unexpected token {self.peek().text!r}")
        return app

    def _is_app_annotation(self) -> bool:
        # @app:name(...)  (reference: app_annotation rule)
        return self.peek(1).type == "ID" and self.peek(1).text.lower() == "app" and self.peek(2).type == ":"

    def _app_annotation(self) -> Annotation:
        self.expect("@")
        self.expect_kw("app")
        self.expect(":")
        name = "app:" + self.name()
        elements = []
        if self.accept("("):
            if not self.at(")"):
                elements.append(self._annotation_element())
                while self.accept(","):
                    elements.append(self._annotation_element())
            self.expect(")")
        return Annotation(name, elements)

    def _annotations(self) -> list[Annotation]:
        anns = []
        while self.at("@"):
            anns.append(self._annotation())
        return anns

    def _annotation(self) -> Annotation:
        self.expect("@")
        name = self.name()
        if self.accept(":"):  # namespaced like @sink:ns? (grammar: name only, but @app:x covered)
            name = f"{name}:{self.name()}"
        elements: list = []
        nested: list[Annotation] = []
        if self.accept("("):
            if not self.at(")"):
                while True:
                    if self.at("@"):
                        nested.append(self._annotation())
                    else:
                        elements.append(self._annotation_element())
                    if not self.accept(","):
                        break
            self.expect(")")
        return Annotation(name, elements, nested)

    def _annotation_element(self) -> tuple[Optional[str], str]:
        # (property_name '=')? property_value ; property_name can be dotted
        if self.peek().type in ("ID", "QID"):
            # property name path: name (sep name)* '='
            start = self.pos
            parts = [self.name()]
            while self.peek().type in (".", "-", ":") and self.peek(1).type in ("ID", "QID"):
                sep = self.next().type
                parts.append(sep + self.name())
            if self.accept("="):
                return ("".join(parts), self._property_value())
            self.pos = start
        return (None, self._property_value())

    def _property_value(self) -> str:
        t = self.peek()
        if t.type == "STRING":
            self.next()
            return t.text
        if t.type in ("INT", "LONG", "FLOAT", "DOUBLE"):
            self.next()
            return str(t.value)
        if t.type in ("ID", "QID"):
            self.next()
            return t.text
        raise self.err(f"expected annotation value, found {t.text!r}")

    # ---- definitions -----------------------------------------------------

    def _definition(self, app: SiddhiApp, anns: list[Annotation]) -> None:
        def_tok = self.peek()
        self.expect_kw("define")
        kind = self.expect_kw(
            "stream", "table", "window", "trigger", "function", "aggregation"
        ).text.lower()
        if kind == "stream":
            d = StreamDefinition(self.name(), self._attr_list(), anns)
            app.define_stream(self.stamp(d, def_tok))
        elif kind == "table":
            d = TableDefinition(self.name(), self._attr_list(), anns)
            app.define_table(self.stamp(d, def_tok))
        elif kind == "window":
            wid = self.name()
            attrs = self._attr_list()
            spec_tok = self.peek()
            ns, fname, params = self._function_operation()
            out = "all"
            if self.accept_kw("output"):
                out = self._output_event_type().value.split()[0]
            spec = self.stamp(WindowSpec(ns, fname, params), spec_tok)
            app.define_window(self.stamp(
                WindowDefinition(wid, attrs, anns, window=spec, output_events=out),
                def_tok,
            ))
        elif kind == "trigger":
            tid = self.name()
            self.expect_kw("at")
            if self.accept_kw("every"):
                ms = self._time_value()
                app.define_trigger(self.stamp(
                    TriggerDefinition(tid, at_every_ms=ms, annotations=anns), def_tok
                ))
            else:
                s = self.expect("STRING").text
                if s.lower() == "start":
                    app.define_trigger(self.stamp(
                        TriggerDefinition(tid, at_start=True, annotations=anns), def_tok
                    ))
                else:
                    app.define_trigger(self.stamp(
                        TriggerDefinition(tid, at_cron=s, annotations=anns), def_tok
                    ))
        elif kind == "function":
            fid = self.name()
            self.expect("[")
            lang = self.name()
            self.expect("]")
            self.expect_kw("return")
            rt = self._attr_type()
            body = self.expect("SCRIPT").text
            app.define_function(self.stamp(
                FunctionDefinition(fid, lang, rt, body, anns), def_tok
            ))
        else:  # aggregation
            aid = self.name()
            self.expect_kw("from")
            stream = self._standard_stream()
            selector = self._query_section(group_by_only=True)
            self.expect_kw("aggregate")
            by = None
            if self.accept_kw("by"):
                by = self._attribute_reference()
            self.expect_kw("every")
            period = self._aggregation_time()
            app.define_aggregation(self.stamp(
                AggregationDefinition(aid, stream, selector, by, period, anns),
                def_tok,
            ))

    def _attr_list(self) -> list[Attribute]:
        self.expect("(")
        tok = self.peek()
        attrs = [self.stamp(Attribute(self.name(), self._attr_type()), tok)]
        while self.accept(","):
            tok = self.peek()
            attrs.append(self.stamp(Attribute(self.name(), self._attr_type()), tok))
        self.expect(")")
        return attrs

    def _attr_type(self) -> AttrType:
        t = self.expect_kw(*_TYPES)
        return _TYPES[t.text.lower()]

    def _aggregation_time(self) -> TimePeriod:
        d1 = _DURATIONS.get(self.name().lower())
        if d1 is None:
            raise self.err("expected aggregation duration")
        if self.accept("..."):
            d2 = _DURATIONS.get(self.name().lower())
            if d2 is None:
                raise self.err("expected aggregation duration")
            return TimePeriod.range(d1, d2)
        durations = [d1]
        while self.accept(","):
            d = _DURATIONS.get(self.name().lower())
            if d is None:
                raise self.err("expected aggregation duration")
            durations.append(d)
        return TimePeriod(durations)

    # ---- partition -------------------------------------------------------

    def _partition(self, anns: list[Annotation]) -> Partition:
        part_tok = self.peek()
        self.expect_kw("partition")
        self.expect_kw("with")
        self.expect("(")
        part = self.stamp(Partition(annotations=anns), part_tok)
        part.partition_types.append(self._partition_with())
        while self.accept(","):
            part.partition_types.append(self._partition_with())
        self.expect(")")
        self.expect_kw("begin")
        while True:
            while self.accept(";"):
                pass
            if self.at_kw("end"):
                break
            q_anns = self._annotations()
            part.queries.append(self._query(q_anns))
        self.expect_kw("end")
        return part

    def _partition_with(self):
        start = self.pos
        start_tok = self.peek()
        expr = self._expression()
        if self.at_kw("as") or self.at_kw("or"):
            # range partition: expr as 'name' (or ...)* of Stream
            self.pos = start
            ranges = []
            while True:
                cond = self._expression()
                self.expect_kw("as")
                label = self.expect("STRING").text
                ranges.append(RangePartitionProperty(label, cond))
                if not self.accept_kw("or"):
                    break
            self.expect_kw("of")
            return self.stamp(RangePartitionType(self.name(), ranges), start_tok)
        self.expect_kw("of")
        return self.stamp(ValuePartitionType(self.name(), expr), start_tok)

    # ---- query -----------------------------------------------------------

    def _query(self, anns: list[Annotation]) -> Query:
        from_tok = self.peek()
        self.expect_kw("from")
        q = self.stamp(Query(annotations=anns), from_tok)
        q.input_stream = self._query_input()
        if self.at_kw("select"):
            q.selector = self._query_section()
        else:
            q.selector = Selector(select_all=True)
        q.output_rate = self._output_rate()
        out_tok = self.peek()
        q.output_stream = self.stamp(self._query_output(), out_tok)
        return q

    def _query_input(self):
        kind = self._classify_input()
        if kind == "pattern":
            return self._state_stream(StateStreamType.PATTERN)
        if kind == "sequence":
            return self._state_stream(StateStreamType.SEQUENCE)
        if kind == "join":
            return self._join_stream()
        return self._standard_stream()

    def _classify_input(self) -> str:
        """Look ahead to decide standard / join / pattern / sequence
        (replaces ANTLR's unbounded-lookahead alternatives)."""
        # brackets hide filter expressions entirely; parens only hide
        # pattern-irrelevant commas (function args) — arrows/aliases inside a
        # parenthesized state block (`(every e1=... -> e2=...) within ...`)
        # still classify as a pattern
        par = 0
        sq = 0
        i = self.pos
        toks = self.toks
        saw_arrow = saw_comma = saw_join = saw_logical = saw_assign = False
        starts_every_or_not = toks[i].type == "ID" and toks[i].text.lower() in ("every", "not")
        while i < len(toks):
            t = toks[i]
            if t.type == "(":
                par += 1
            elif t.type == ")":
                par -= 1
                if par < 0:
                    break
            elif t.type == "[":
                sq += 1
            elif t.type == "]":
                sq -= 1
                if sq < 0:
                    break
            elif sq == 0:
                if t.type == "->":
                    saw_arrow = True
                elif t.type == "," and par == 0:
                    saw_comma = True
                elif t.type == "=":
                    saw_assign = True
                elif t.type == "ID" and par == 0:
                    low = t.text.lower()
                    if low in ("select", "output", "insert", "delete", "update", "return"):
                        break
                    if low == "join" or (
                        low in ("left", "right", "full", "inner", "outer")
                        and i + 1 < len(toks)
                    ):
                        if low == "join":
                            saw_join = True
                    elif low in ("and", "or"):
                        saw_logical = True
            i += 1
        if saw_join:
            # JOIN at depth 0 can only be a join query (filters keep and/or and
            # commas inside brackets; aggregation joins add within-clause commas)
            return "join"
        if saw_comma and (saw_arrow or saw_assign or starts_every_or_not or saw_logical):
            return "sequence"
        if saw_arrow or saw_assign or starts_every_or_not or saw_logical:
            return "pattern"
        if saw_comma:
            return "sequence"
        return "standard"

    # --- standard stream

    def _standard_stream(self) -> SingleInputStream:
        s = self._source()
        self._stream_handlers(s)
        return s

    def _source(self) -> SingleInputStream:
        tok = self.peek()
        inner = bool(self.accept("#"))
        # `!S` consumes S's fault stream (reference: SiddhiQL.g4 fault streams,
        # keyed internally under the '!'-prefixed id)
        fault = False if inner else bool(self.accept("!"))
        name = self.name()
        return self.stamp(
            SingleInputStream(
                ("!" + name) if fault else name, is_inner=inner, is_fault=fault
            ),
            tok,
        )

    def _stream_handlers(self, s: SingleInputStream) -> None:
        while True:
            tok = self.peek()
            if self.at("["):
                self.next()
                s.handlers.append(self.stamp(Filter(self._expression()), tok))
                self.expect("]")
            elif self.at("#"):
                # '#window.x(...)' | '#ns:func(...)' | '#func(...)' | '#[filter]'
                nxt = self.peek(1)
                if nxt.type == "[":
                    self.next()
                    continue
                if nxt.type != "ID":
                    break
                self.next()
                if self.at_kw("window") and self.peek(1).type == ".":
                    self.next()
                    self.next()
                    spec_tok = self.peek()
                    ns, name, params = self._function_operation()
                    spec = self.stamp(WindowSpec(ns, name, params), spec_tok)
                    s.handlers.append(self.stamp(WindowHandler(spec), tok))
                else:
                    ns, name, params = self._function_operation()
                    s.handlers.append(
                        self.stamp(StreamFunctionHandler(ns, name, params), tok)
                    )
            else:
                break

    # --- join

    def _join_stream(self) -> JoinInputStream:
        left, l_uni = self._join_source()
        jt = self._join_kind()
        right, r_uni = self._join_source()
        uni = "left" if l_uni else ("right" if r_uni else None)
        on = within = per = None
        if self.accept_kw("on"):
            on = self._expression()
        if self.accept_kw("within"):
            within = self._expression()
            if self.accept(","):
                # within start, end — packed as a pair by the aggregation-join layer
                end = self._expression()
                within = AttributeFunction(None, "__within_range__", [within, end])
        if self.accept_kw("per"):
            per = self._expression()
        return JoinInputStream(left, jt, right, on=on, within=within, per=per, unidirectional=uni)

    def _join_source(self) -> tuple[SingleInputStream, bool]:
        s = self._source()
        self._stream_handlers(s)
        if self.accept_kw("as"):
            s.alias = self.name()
        uni = bool(self.accept_kw("unidirectional"))
        return s, uni

    def _join_kind(self) -> JoinType:
        if self.accept_kw("join"):
            return JoinType.JOIN
        if self.accept_kw("inner"):
            self.expect_kw("join")
            return JoinType.JOIN
        if self.accept_kw("left"):
            self.expect_kw("outer")
            self.expect_kw("join")
            return JoinType.LEFT_OUTER
        if self.accept_kw("right"):
            self.expect_kw("outer")
            self.expect_kw("join")
            return JoinType.RIGHT_OUTER
        if self.accept_kw("full"):
            self.expect_kw("outer")
            self.expect_kw("join")
            return JoinType.FULL_OUTER
        if self.accept_kw("outer"):
            self.expect_kw("join")
            return JoinType.FULL_OUTER
        raise self.err("expected join")

    # --- pattern / sequence

    def _state_stream(self, kind: StateStreamType) -> StateInputStream:
        sep = "->" if kind is StateStreamType.PATTERN else ","
        elem = self._state_chain(sep)
        within = None
        if self.accept_kw("within"):
            within = self._time_value()
        return StateInputStream(kind, elem, within_ms=within)

    def _state_chain(self, sep: str) -> StateElement:
        elem = self._state_term(sep)
        while self.at(sep):
            tok = self.next()
            nxt = self._state_term(sep)
            elem = self.stamp(NextStateElement(elem, nxt), tok)
        return elem

    def _state_term(self, sep: str) -> StateElement:
        tok = self.peek()
        every = bool(self.accept_kw("every"))
        if self.accept("("):
            inner = self._state_chain(sep)
            self.expect(")")
            elem = inner
        else:
            elem = self._pattern_source(sep)
        if every:
            elem = self.stamp(EveryStateElement(elem), tok)
        if self.at_kw("within"):
            self.next()
            elem.within_ms = self._time_value()
        return elem

    def _pattern_source(self, sep: str) -> StateElement:
        left = self._single_or_absent(sep)
        if self.at_kw("and", "or"):
            tok = self.peek()
            op = LogicalType(self.next().text.lower())
            right = self._single_or_absent(sep)
            return self.stamp(LogicalStateElement(left, op, right), tok)
        return left

    def _single_or_absent(self, sep: str) -> StateElement:
        # absent source: not S[...] (for t)?  — absent may appear on either or
        # both sides of a logical element (reference: logical_absent_stateful)
        tok = self.peek()
        if self.accept_kw("not"):
            s = self._basic_source()
            waiting = None
            if self.accept_kw("for"):
                waiting = self._time_value()
            return self.stamp(
                AbsentStreamStateElement(stream=s, waiting_time_ms=waiting), tok
            )
        return self._pattern_single(sep)

    def _pattern_single(self, sep: str) -> StateElement:
        # (event '=')? basic_source ('<' collect '>' | * + ?)?
        tok = self.peek()
        alias = None
        if (
            self.peek().type in ("ID", "QID")
            and self.peek(1).type == "="
            and self.peek(2).type != "="
        ):
            alias = self.name()
            self.next()  # '='
        s = self._basic_source()
        s.alias = alias
        elem = self.stamp(StreamStateElement(stream=s), tok)
        if self.at("<"):
            self.next()
            mn, mx = self._collect()
            self.expect(">")
            return self.stamp(CountStateElement(elem, mn, mx), tok)
        if sep == "," and self.peek().type in ("*", "+", "?"):
            suffix = self.next().type
            if suffix == "*":
                return self.stamp(CountStateElement(elem, 0, CountStateElement.ANY), tok)
            if suffix == "+":
                return self.stamp(CountStateElement(elem, 1, CountStateElement.ANY), tok)
            return self.stamp(CountStateElement(elem, 0, 1), tok)
        return elem

    def _basic_source(self) -> SingleInputStream:
        s = self._source()
        # only filters/stream functions (no windows) on pattern sources
        while True:
            tok = self.peek()
            if self.at("["):
                self.next()
                s.handlers.append(self.stamp(Filter(self._expression()), tok))
                self.expect("]")
            elif self.at("#") and self.peek(1).type == "ID":
                self.next()
                ns, name, params = self._function_operation()
                s.handlers.append(
                    self.stamp(StreamFunctionHandler(ns, name, params), tok)
                )
            else:
                break
        return s

    def _collect(self) -> tuple[int, int]:
        mn = mx = CountStateElement.ANY
        if self.at("INT"):
            mn = int(self.next().value)
            if self.accept(":"):
                if self.at("INT"):
                    mx = int(self.next().value)
            else:
                mx = mn
        elif self.accept(":"):
            mn = 0
            mx = int(self.expect("INT").value)
        if mn == CountStateElement.ANY:
            mn = 0
        return mn, mx

    # --- selector

    def _query_section(self, group_by_only: bool = False) -> Selector:
        sel_tok = self.peek()
        self.expect_kw("select")
        sel = self.stamp(Selector(), sel_tok)
        if self.accept("*"):
            sel.select_all = True
        else:
            sel.selection_list.append(self._output_attribute())
            while self.accept(","):
                sel.selection_list.append(self._output_attribute())
        if self.at_kw("group"):
            self.next()
            self.expect_kw("by")
            sel.group_by.append(self._attribute_reference())
            while self.accept(","):
                sel.group_by.append(self._attribute_reference())
        if group_by_only:
            return sel
        if self.accept_kw("having"):
            sel.having = self._expression()
        if self.at_kw("order"):
            self.next()
            self.expect_kw("by")
            while True:
                v = self._attribute_reference()
                order = OrderDir.ASC
                if self.at_kw("asc", "desc"):
                    order = OrderDir(self.next().text.lower())
                sel.order_by.append(OrderByAttribute(v, order))
                if not self.accept(","):
                    break
        if self.accept_kw("limit"):
            c = self._expression()
            sel.limit = _const_int(c, self.err)
        if self.accept_kw("offset"):
            c = self._expression()
            sel.offset = _const_int(c, self.err)
        return sel

    def _output_attribute(self) -> OutputAttribute:
        tok = self.peek()
        e = self._expression()
        rename = None
        if self.accept_kw("as"):
            rename = self.name()
        return self.stamp(OutputAttribute(rename, e), tok)

    # --- output rate & output

    def _output_rate(self):
        if not self.at_kw("output"):
            return None
        # `output` may begin the rate clause OR nothing (outputs are insert/..)
        nxt = self.peek(1)
        if not (
            (nxt.type == "ID" and nxt.text.lower() in ("all", "first", "last", "every", "snapshot"))
        ):
            return None
        self.next()
        if self.accept_kw("snapshot"):
            self.expect_kw("every")
            return SnapshotOutputRate(self._time_value())
        rtype = OutputRateType.ALL
        if self.at_kw("all", "first", "last"):
            rtype = OutputRateType(self.next().text.lower())
        self.expect_kw("every")
        if self.at("INT") and self.peek(1).type == "ID" and self.peek(1).text.lower() in ("events", "event"):
            nvalue = int(self.next().value)
            self.next()
            return EventOutputRate(nvalue, rtype)
        return TimeOutputRate(self._time_value(), rtype)

    def _query_output(self):
        if self.accept_kw("insert"):
            out_for = OutputEventsFor.CURRENT
            if self.at_kw("all", "expired", "current"):
                out_for = self._output_event_type()
            elif self.at_kw("events"):
                self.next()
            self.expect_kw("into")
            inner = bool(self.accept("#"))
            fault = False if inner else bool(self.accept("!"))
            name = self.name()
            return InsertIntoStream(
                out_for,
                ("!" + name) if fault else name,
                is_inner=inner,
                is_fault=fault,
            )
        if self.accept_kw("delete"):
            target = self.name()
            out_for = OutputEventsFor.CURRENT
            if self.accept_kw("for"):
                out_for = self._output_event_type()
            self.expect_kw("on")
            return DeleteStream(out_for, target, on=self._expression())
        if self.accept_kw("update"):
            if self.accept_kw("or"):
                self.expect_kw("insert")
                self.expect_kw("into")
                cls = UpdateOrInsertStream
            else:
                cls = UpdateStream
            target = self.name()
            out_for = OutputEventsFor.CURRENT
            if self.accept_kw("for"):
                out_for = self._output_event_type()
            set_attrs = self._set_clause()
            self.expect_kw("on")
            return cls(out_for, target, on=self._expression(), set_attributes=set_attrs)
        if self.accept_kw("return"):
            out_for = OutputEventsFor.CURRENT
            if self.at_kw("all", "expired", "current", "events"):
                out_for = self._output_event_type()
            return ReturnStream(out_for)
        # bare query (no output clause) returns
        return ReturnStream()

    def _set_clause(self):
        if not self.at_kw("set"):
            return None
        self.next()
        out = []
        while True:
            v = self._attribute_reference()
            self.expect("=")
            out.append(UpdateSetAttribute(v, self._expression()))
            if not self.accept(","):
                break
        return out

    def _output_event_type(self) -> OutputEventsFor:
        if self.accept_kw("all"):
            self.expect_kw("events")
            return OutputEventsFor.ALL
        if self.accept_kw("expired"):
            self.expect_kw("events")
            return OutputEventsFor.EXPIRED
        self.accept_kw("current")
        self.expect_kw("events")
        return OutputEventsFor.CURRENT

    # ---- store query -----------------------------------------------------

    def parse_store_query(self) -> StoreQuery:
        sq = StoreQuery()
        self.stamp(sq, self.peek())
        if self.accept_kw("from"):
            store_tok = self.peek()
            store_id = self.name()
            store = self.stamp(InputStore(store_id), store_tok)
            if self.accept_kw("as"):
                store.alias = self.name()
            if self.accept_kw("on"):
                store.on = self._expression()
            if self.accept_kw("within"):
                start = self._expression()
                end = None
                if self.accept(","):
                    end = self._expression()
                store.within = (start, end)
            if self.accept_kw("per"):
                store.per = self._expression()
            sq.input_store = store
            if self.at_kw("select"):
                sq.selector = self._query_section()
            else:
                sq.selector = Selector(select_all=True)
            if self.at_kw("update", "delete", "insert"):
                sq.output_stream = self._query_output()
        else:
            sq.selector = self._query_section()
            sq.output_stream = self._query_output()
        self.accept(";")
        self.expect("EOF")
        return sq

    # ---- expressions -----------------------------------------------------

    def _expression(self) -> Expression:
        return self._or_expr()

    def _or_expr(self) -> Expression:
        e = self._and_expr()
        while self.at_kw("or"):
            tok = self.next()
            e = self.stamp(Or(e, self._and_expr()), tok)
        return e

    def _and_expr(self) -> Expression:
        e = self._in_expr()
        while self.at_kw("and"):
            tok = self.next()
            e = self.stamp(And(e, self._in_expr()), tok)
        return e

    def _in_expr(self) -> Expression:
        e = self._equality()
        while self.at_kw("in"):
            tok = self.next()
            e = self.stamp(In(e, self.name()), tok)
        return e

    def _equality(self) -> Expression:
        e = self._relational()
        while self.peek().type in ("==", "!="):
            tok = self.next()
            op = CompareOp(tok.type)
            e = self.stamp(Compare(e, op, self._relational()), tok)
        return e

    def _relational(self) -> Expression:
        e = self._additive()
        while self.peek().type in ("<", "<=", ">", ">="):
            tok = self.next()
            op = CompareOp(tok.type)
            e = self.stamp(Compare(e, op, self._additive()), tok)
        return e

    def _additive(self) -> Expression:
        e = self._multiplicative()
        while self.peek().type in ("+", "-"):
            tok = self.next()
            rhs = self._multiplicative()
            e = self.stamp(Add(e, rhs) if tok.type == "+" else Subtract(e, rhs), tok)
        return e

    def _multiplicative(self) -> Expression:
        e = self._unary()
        while self.peek().type in ("*", "/", "%"):
            tok = self.next()
            rhs = self._unary()
            e = self.stamp({"*": Multiply, "/": Divide, "%": Mod}[tok.type](e, rhs), tok)
        return e

    def _unary(self) -> Expression:
        if self.at_kw("not"):
            tok = self.next()
            return self.stamp(Not(self._unary()), tok)
        if self.peek().type in ("-", "+"):
            sign = self.next().type
            t = self.peek()
            if t.type not in ("INT", "LONG", "FLOAT", "DOUBLE"):
                raise self.err("expected numeric literal after unary sign")
            e = self._primary()
            if sign == "-":
                assert isinstance(e, Constant)
                e.value = -e.value
            return e
        return self._primary()

    def _primary(self) -> Expression:
        t = self.peek()
        if t.type == "(":
            self.next()
            e = self._expression()
            self.expect(")")
            return self._maybe_is_null(e)
        if t.type == "INT":
            # time constant? INT followed by a time unit identifier
            if self.peek(1).type == "ID" and self.peek(1).text.lower() in TIME_UNITS:
                return self.stamp(TimeConstant(self._time_value()), t)
            self.next()
            return self.stamp(Constant(int(t.value), AttrType.INT), t)
        if t.type == "LONG":
            self.next()
            return self.stamp(Constant(int(t.value), AttrType.LONG), t)
        if t.type == "FLOAT":
            self.next()
            return self.stamp(Constant(float(t.value), AttrType.FLOAT), t)
        if t.type == "DOUBLE":
            self.next()
            return self.stamp(Constant(float(t.value), AttrType.DOUBLE), t)
        if t.type == "STRING":
            self.next()
            return self.stamp(Constant(t.text, AttrType.STRING), t)
        if t.type in ("ID", "QID", "#"):
            low = t.text.lower() if t.type == "ID" else ""
            if low == "true":
                self.next()
                return self.stamp(Constant(True, AttrType.BOOL), t)
            if low == "false":
                self.next()
                return self.stamp(Constant(False, AttrType.BOOL), t)
            if low == "null":
                self.next()
                return self.stamp(Constant(None, AttrType.OBJECT), t)
            return self._maybe_is_null(self._ref_or_function())
        raise self.err(f"unexpected token {t.text!r} in expression")

    def _maybe_is_null(self, e: Expression) -> Expression:
        if self.at_kw("is") and self.peek(1).type == "ID" and self.peek(1).text.lower() == "null":
            tok = self.next()
            self.next()
            if isinstance(e, Variable) and e.stream_id is not None and e.attribute == "":
                # explicit stream reference form: `e1[0] is null`
                return self.stamp(
                    IsNull(stream_id=e.stream_id, stream_index=e.stream_index), tok
                )
            if isinstance(e, Variable) and e.stream_id is None:
                # bare `name is null` is ambiguous: attribute or pattern state
                # alias. Keep both readings; the compile layer prefers a state
                # alias when one matches (reference null_check rule has the
                # same ambiguity resolved in the visitor).
                return self.stamp(IsNull(expression=e, stream_id=e.attribute), tok)
            return self.stamp(IsNull(expression=e), tok)
        return e

    def _ref_or_function(self) -> Expression:
        # function: (ns ':')? name '(' ... ')'
        if self.peek().type in ("ID", "QID"):
            tok = self.peek()
            if self.peek(1).type == "(":
                fname = self.name()
                return self.stamp(self._finish_function(None, fname), tok)
            if (
                self.peek(1).type == ":"
                and self.peek(2).type in ("ID", "QID")
                and self.peek(3).type == "("
            ):
                ns = self.name()
                self.next()
                fname = self.name()
                return self.stamp(self._finish_function(ns, fname), tok)
        return self._attribute_reference(allow_stream_ref=True)

    def _finish_function(self, ns: Optional[str], fname: str) -> Expression:
        self.expect("(")
        params: list[Expression] = []
        if not self.at(")"):
            if self.accept("*"):
                pass  # count(*) style — no parameters
            else:
                params.append(self._expression())
                while self.accept(","):
                    params.append(self._expression())
        self.expect(")")
        return AttributeFunction(ns, fname, params)

    def _attribute_reference(self, allow_stream_ref: bool = False) -> Variable:
        # [#]name[idx][#name2[idx2]].attr | attr
        tok = self.peek()
        inner = bool(self.accept("#"))
        name1 = self.name()
        idx = None
        if self.at("["):
            self.next()
            idx = self._attribute_index()
            self.expect("]")
        if self.accept("#"):
            # partition inner-stream double ref: name1#name2 — keep last part
            name2 = self.name()
            if self.at("["):
                self.next()
                idx = self._attribute_index()
                self.expect("]")
            name1 = f"{name1}#{name2}"
        if self.accept("."):
            attr = self.name()
            return self.stamp(
                Variable(attr, stream_id=name1, stream_index=idx, is_inner=inner), tok
            )
        if idx is not None:
            # indexed bare stream reference (only meaningful before IS NULL)
            return self.stamp(
                Variable("", stream_id=name1, stream_index=idx, is_inner=inner), tok
            )
        return self.stamp(Variable(name1, is_inner=inner), tok)

    def _attribute_index(self) -> int:
        if self.at("INT"):
            return int(self.next().value)
        t = self.expect_kw("last")
        if self.accept("-"):
            return Variable.LAST - int(self.expect("INT").value)
        return Variable.LAST

    # ---- time ------------------------------------------------------------

    def _time_value(self) -> int:
        total = 0
        seen = False
        while self.at("INT") and self.peek(1).type == "ID" and self.peek(1).text.lower() in TIME_UNITS:
            n = int(self.next().value)
            unit = self.next().text.lower()
            total += n * TIME_UNITS[unit]
            seen = True
        if not seen:
            raise self.err("expected time value (e.g. `5 sec`)")
        return total

    def _function_operation(self) -> tuple[Optional[str], str, list[Expression]]:
        name1 = self.name()
        ns = None
        if self.accept(":"):
            ns = name1
            name1 = self.name()
        self.expect("(")
        params: list[Expression] = []
        if not self.at(")"):
            if self.accept("*"):
                pass
            else:
                params.append(self._expression())
                while self.accept(","):
                    params.append(self._expression())
        self.expect(")")
        return ns, name1, params


def _const_int(e: Expression, err) -> int:
    if isinstance(e, Constant) and isinstance(e.value, int):
        return int(e.value)
    raise err("expected integer constant")
