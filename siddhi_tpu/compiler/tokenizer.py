"""SiddhiQL tokenizer.

Covers the lexical surface of the reference grammar
(reference: siddhi-query-compiler .../SiddhiQL.g4:500-913): case-insensitive
keywords (matched parser-side so keywords stay usable as names), `[a-zA-Z_]\\w*`
identifiers, backquoted identifiers, '/"/triple-quoted strings, numeric literals
with L/F/D suffixes and exponents, `--` and `/* */` comments, balanced-brace
SCRIPT bodies, and the operator/punctuation set including `->` and `...`.
"""

from __future__ import annotations

import dataclasses

from siddhi_tpu.core.errors import SiddhiParserError


@dataclasses.dataclass
class Token:
    type: str  # ID, QID, INT, LONG, FLOAT, DOUBLE, STRING, SCRIPT, op text, EOF
    value: object
    line: int
    col: int

    @property
    def text(self) -> str:
        return "<end of input>" if self.type == "EOF" else str(self.value)


_PUNCT = [
    "...", "->", "<=", ">=", "==", "!=",
    ":", ";", ".", "(", ")", "[", "]", ",", "=", "*", "+", "?", "-", "/", "%",
    "<", ">", "@", "#", "!",
]


def tokenize(src: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(src)
    line, col = 1, 1

    def advance(k: int) -> None:
        nonlocal i, line, col
        for _ in range(k):
            if i < n and src[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    def err(msg: str) -> SiddhiParserError:
        return SiddhiParserError(msg, line, col)

    while i < n:
        c = src[i]
        # whitespace
        if c in " \t\r\n\x0b":
            advance(1)
            continue
        # comments
        if src.startswith("--", i):
            while i < n and src[i] != "\n":
                advance(1)
            continue
        if src.startswith("/*", i):
            end = src.find("*/", i + 2)
            stop = n if end < 0 else end + 2
            advance(stop - i)
            continue
        tl, tc = line, col
        # strings
        if src.startswith('"""', i):
            end = src.find('"""', i + 3)
            if end < 0:
                raise err("unterminated triple-quoted string")
            toks.append(Token("STRING", src[i + 3 : end], tl, tc))
            advance(end + 3 - i)
            continue
        if c in "'\"":
            j = i + 1
            while j < n and src[j] != c:
                if src[j] == "\n":
                    raise err("unterminated string literal")
                j += 1
            if j >= n:
                raise err("unterminated string literal")
            toks.append(Token("STRING", src[i + 1 : j], tl, tc))
            advance(j + 1 - i)
            continue
        # backquoted identifier
        if c == "`":
            j = src.find("`", i + 1)
            if j < 0:
                raise err("unterminated quoted identifier")
            toks.append(Token("QID", src[i + 1 : j], tl, tc))
            advance(j + 1 - i)
            continue
        # script body { ... } with balanced braces
        if c == "{":
            depth, j = 0, i
            while j < n:
                if src[j] == "{":
                    depth += 1
                elif src[j] == "}":
                    depth -= 1
                    if depth == 0:
                        break
                elif src[j] in "'\"":
                    q = src[j]
                    j += 1
                    while j < n and src[j] != q:
                        j += 1
                j += 1
            if depth != 0:
                raise err("unbalanced '{' in script body")
            toks.append(Token("SCRIPT", src[i + 1 : j], tl, tc))
            advance(j + 1 - i)
            continue
        # numbers (a leading '.' digit form too)
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n:
                ch = src[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    # '...' (aggregation range) must not be eaten by a number
                    if src.startswith("...", j):
                        break
                    # require digit or end-ish after '.': '1.sec'? reference
                    # FLOAT allows '1.'; keep permissive unless followed by '.'
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j + 1 < n and (
                    src[j + 1].isdigit() or (src[j + 1] in "+-" and j + 2 < n and src[j + 2].isdigit())
                ):
                    seen_exp = True
                    j += 1
                    if src[j] in "+-":
                        j += 1
                else:
                    break
            body = src[i:j]
            suffix = src[j].upper() if j < n and src[j].upper() in ("L", "F", "D") else ""
            # a suffix letter must not begin a longer identifier (e.g. `5 days`
            # lexes INT(5) ID(days), but `5L` is LONG) — except that `10f`/`10d`
            # glued to an id char is invalid anyway
            if suffix and (j + 1 >= n or not (src[j + 1].isalnum() or src[j + 1] == "_")):
                j += 1
            else:
                suffix = ""
            if suffix == "L":
                if seen_dot or seen_exp:
                    raise err(f"invalid long literal {body + 'L'!r}")
                toks.append(Token("LONG", int(body), tl, tc))
            elif suffix == "F":
                toks.append(Token("FLOAT", float(body), tl, tc))
            elif suffix == "D":
                toks.append(Token("DOUBLE", float(body), tl, tc))
            elif seen_dot or seen_exp:
                toks.append(Token("DOUBLE", float(body), tl, tc))
            else:
                toks.append(Token("INT", int(body), tl, tc))
            advance(j - i)
            continue
        # identifiers
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            toks.append(Token("ID", src[i:j], tl, tc))
            advance(j - i)
            continue
        # punctuation / operators
        for p in _PUNCT:
            if src.startswith(p, i):
                toks.append(Token(p, p, tl, tc))
                advance(len(p))
                break
        else:
            raise err(f"unexpected character {c!r}")
    toks.append(Token("EOF", None, line, col))
    return toks


# time units (singular/plural/abbreviated) -> milliseconds
# (reference: SiddhiQL.g4 time_value / YEARS..MILLISECONDS token rules)
TIME_UNITS = {
    "year": 365 * 86_400_000, "years": 365 * 86_400_000,
    "month": 30 * 86_400_000, "months": 30 * 86_400_000,
    "week": 7 * 86_400_000, "weeks": 7 * 86_400_000,
    "day": 86_400_000, "days": 86_400_000,
    "hour": 3_600_000, "hours": 3_600_000,
    "min": 60_000, "minute": 60_000, "minutes": 60_000,
    "sec": 1_000, "second": 1_000, "seconds": 1_000,
    "millisec": 1, "millisecond": 1, "milliseconds": 1,
}
