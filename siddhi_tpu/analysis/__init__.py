"""siddhi_tpu.analysis — compile-time semantic analyzer + SiddhiQL linter.

Public API:

    from siddhi_tpu.analysis import analyze
    result = analyze(app_or_source)     # SiddhiApp AST or SiddhiQL text
    result.ok, result.errors, result.warnings
    result.raise_if_errors()            # -> SiddhiAnalysisError

Integration points:

* `SiddhiManager.create_siddhi_app_runtime(app, strict=True)` (alias
  `create_runtime`) runs this pass first and raises one
  `SiddhiAnalysisError` aggregating every error diagnostic.
* CLI: `python -m siddhi_tpu.analysis app.siddhi [--format=text|json]
  [--werror]` — stable SA### codes documented in the README.
"""

from __future__ import annotations

from typing import Union

from siddhi_tpu.analysis.analyzer import analyze as _analyze_app
from siddhi_tpu.analysis.analyzer import analyze_store_query
from siddhi_tpu.analysis.diagnostics import (
    CODES,
    ERROR,
    WARNING,
    AnalysisResult,
    Diagnostic,
    SiddhiAnalysisError,
)
from siddhi_tpu.query_api.siddhi_app import SiddhiApp

__all__ = [
    "analyze",
    "analyze_add_query",
    "analyze_store_query",
    "build_fusion_plan",
    "compute_costs",
    "AnalysisResult",
    "Diagnostic",
    "SiddhiAnalysisError",
    "CODES",
    "ERROR",
    "WARNING",
]


def _to_app(app: "Union[str, SiddhiApp]") -> SiddhiApp:
    if isinstance(app, str):
        from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

        app = SiddhiCompiler.parse(app)
    return app


def build_fusion_plan(app: "Union[str, SiddhiApp]"):
    """Static FusionPlan (analysis/fusion.py) for an app (AST or source)."""
    from siddhi_tpu.analysis.fusion import build_fusion_plan as _plan

    return _plan(_to_app(app))


def compute_costs(app: "Union[str, SiddhiApp]"):
    """Static AppCostModel (analysis/cost.py) for an app (AST or source)."""
    from siddhi_tpu.analysis.cost import compute_costs as _costs

    return _costs(_to_app(app))


def analyze(app: Union[str, SiddhiApp]) -> AnalysisResult:
    """Semantic analysis of a SiddhiApp (AST or SiddhiQL source text)."""
    return _analyze_app(_to_app(app))


def analyze_add_query(app: "Union[str, SiddhiApp]", query) -> AnalysisResult:
    """SA130: lint a hot `add_query` candidate against a LIVE app's symbols
    (duplicate query id, undeclared stream) — the SAME rule set
    `runtime.add_query` raises on (core/churn.iter_add_query_problems),
    following the SA125–SA129 shared-rule-set pattern. `query` is SiddhiQL
    query text or a Query AST; `app` is the deployed app (AST or source)."""
    from siddhi_tpu.core.churn import iter_add_query_problems

    app = _to_app(app)
    if isinstance(query, str):
        from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

        query = SiddhiCompiler.parse_query(query)
    diags = [
        Diagnostic("SA130", problem)
        for problem in iter_add_query_problems(app, query)
    ]
    return AnalysisResult(app_name=app.name or "", diagnostics=diags)
