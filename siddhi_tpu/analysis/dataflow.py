"""Stream -> query dataflow graph: dead streams, unfed windows, cycles.

All findings here are warnings (SA4xx): the runtime supports cyclic
topologies (the app-level processing lock exists for exactly that), input
handlers can feed any defined stream from outside, and callback-only egress
streams are legitimate — so none of these shapes is *wrong*, they are just
worth a look.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from siddhi_tpu.analysis.diagnostics import WARNING, Diagnostic


@dataclasses.dataclass
class QueryFlow:
    """One query's dataflow contribution: consumed stream ids -> produced
    stream id (insert-into target; None for return/table outputs)."""

    qid: str
    consumes: set
    produces: Optional[str] = None


def check_dataflow(app, sym, flows: list[QueryFlow], diags: list[Diagnostic]) -> None:
    consumed: set = set()
    produced: set = set()
    for f in flows:
        consumed.update(f.consumes)
        if f.produces is not None:
            produced.add(f.produces)

    # SA401: streams that participate in nothing at all — not consumed, not
    # produced, no transport, not a fault parent whose '!S' is consumed
    for sid, d in app.stream_definitions.items():
        if sid in consumed or sid in produced:
            continue
        if sid in sym.sourced or sid in sym.sinked:
            continue
        if ("!" + sid) in consumed or ("!" + sid) in produced:
            continue
        diags.append(Diagnostic(
            "SA401",
            f"dead stream: '{sid}' is defined but never consumed or produced "
            "by any query, aggregation, source, or sink",
            getattr(d, "line", None), getattr(d, "col", None),
            severity=WARNING,
        ))

    # SA402: named windows consumed by queries but never fed by an insert
    for wid, d in app.window_definitions.items():
        if wid in consumed and wid not in produced:
            diags.append(Diagnostic(
                "SA402",
                f"named window '{wid}' is consumed but no query inserts into "
                "it — its consumers can only fire on direct input-handler "
                "sends",
                getattr(d, "line", None), getattr(d, "col", None),
                severity=WARNING,
            ))

    # SA403: cycles in the stream graph (edges: each consumed -> produced)
    edges: dict[str, set] = {}
    for f in flows:
        if f.produces is None:
            continue
        for c in f.consumes:
            edges.setdefault(c, set()).add(f.produces)

    cycle = _find_cycle(edges)
    if cycle:
        diags.append(Diagnostic(
            "SA403",
            "stream dataflow cycle: " + " -> ".join(cycle)
            + " (events may loop; ensure a filter breaks the feedback)",
            severity=WARNING,
        ))


def _find_cycle(edges: dict) -> Optional[list]:
    """First cycle in the graph as a node path, or None (iterative DFS)."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color: dict = {}
    parent: dict = {}
    for root in sorted(edges):
        if color.get(root, WHITE) != WHITE:
            continue
        stack = [(root, iter(sorted(edges.get(root, ()))))]
        color[root] = GRAY
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                c = color.get(nxt, WHITE)
                if c == GRAY:
                    # found: unwind the gray path from node back to nxt
                    path = [nxt, node]
                    cur = node
                    while cur != nxt and cur in parent:
                        cur = parent[cur]
                        path.append(cur)
                    path.reverse()
                    return path
                if c == WHITE:
                    color[nxt] = GRAY
                    parent[nxt] = node
                    stack.append((nxt, iter(sorted(edges.get(nxt, ())))))
                    advanced = True
                    break
            if not advanced:
                color[node] = BLACK
                stack.pop()
    return None
