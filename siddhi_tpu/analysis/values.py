"""Abstract-interpretation value analysis over the query graph.

Propagates per-attribute abstract domains — integer intervals, small
constant sets (including low-cardinality string sets), null-ability, and
monotonicity — from literals, filter predicates, selector arithmetic, and
declared `@app:wire` contracts, through multi-hop insert-into chains,
joins (per-side domains), windows, and partitions, to a fixpoint (TiLT's
derive-layout-from-the-IR move, PAPERS.md, applied to SiddhiQL).

The domain lattice, per attribute (`ValueFact`):

* ``interval``  — integer bounds `[lo, hi]`, either side open (None).
  INT/LONG only; floats never carry intervals.
* ``consts``    — the set of values the attribute can possibly hold, when
  provably small (<= MAX_CONSTS): int literals, or raw string literals on
  interned columns. None = unknown/unbounded.
* ``card``      — cardinality bound without known values (from a declared
  `dict` hint, or len(consts)).
* ``nullable``  — False only when provably non-null (literals, arithmetic
  over non-null operands).
* ``monotone``  — non-decreasing in stream order. Seeded from declared
  `delta` hints and from the EVENT-TIME CONTRACT: a LONG/INT attribute
  some consumer uses as the time attribute of `#window.externalTime` /
  `#window.externalTimeBatch` is the stream's event clock, which the
  engine (and PR 14's watermark reorder stage) treats as ordered.
  Survives filters, plain insert-into chains, non-reordering windows
  emitting CURRENT events, and `x + c` / `x * positive-c` arithmetic;
  dies at joins, patterns, group-by, order-by, and expired-event outputs.

Fixpoint & widening: DECLARED streams start from their external
contribution (TOP per attribute, refined by `@app:wire` contracts and the
event-time rule — external senders may inject anything the contract
allows), then JOIN in every in-graph producer's output facts. Streams
that exist only as insert-into targets start at BOTTOM and take exactly
the join of their producers. Queries are re-run in execution order until
nothing changes; an attribute whose interval/constant-set is still
growing after WIDEN_AFTER joins is widened (the growing bound opens to
None, the set drops to unknown), so cyclic insert-into graphs terminate
instead of counting to 2^63.

Consumers of the facts:

* inferred wire specs — `infer_wire_hints()` turns proven facts into the
  same hint tuples `@app:wire` declares (interval -> range/narrow, small
  constant set -> dict, monotone -> delta int16), consumed by
  `core/wire.py build_wire_spec(..., inferred=...)`. Declared hints win
  per lane; every inferred encoder rides the existing per-chunk misfit
  guard, so a wrong proof can only cost a full-width rebuild, never
  wrong bytes.
* query rewriting — `rewrites` notes (constant-folded selector
  expressions, provably-true filter conjuncts, provably-false filters,
  dead columns no consumer reads), surfaced in the FusionPlan (v3) and
  `runtime.explain()`. Purely advisory: execution is not changed, so the
  wire parity contract is untouched.
* lints — SA135 (provably-false filter / unreachable query), SA136
  (comparison that can never vary), SA137 (arithmetic overflow /
  division by zero on a proven domain), SA138 (inferred-encodable
  dominant wide column — the actionable successor to SA133).

Everything here is a pure AST pass: deterministic iteration order
(execution-id order for queries, schema order for attributes), so plan
JSON is byte-stable across runs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from siddhi_tpu.core.types import AttrType
from siddhi_tpu.query_api.annotation import find_annotation
from siddhi_tpu.query_api.execution import (
    Filter,
    JoinInputStream,
    OutputEventsFor,
    Query,
    SingleInputStream,
    StateInputStream,
    StreamFunctionHandler,
    WindowHandler,
    iter_state_streams,
)
from siddhi_tpu.query_api.expression import (
    Add,
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    Divide,
    Expression,
    In,
    IsNull,
    Mod,
    Multiply,
    Not,
    Or,
    Subtract,
    Variable,
)
from siddhi_tpu.query_api.siddhi_app import SiddhiApp

_INTEGRAL = (AttrType.INT, AttrType.LONG)
_INTERNED = (AttrType.STRING, AttrType.OBJECT)

TYPE_BOUNDS = {
    AttrType.INT: (-(2 ** 31), 2 ** 31 - 1),
    AttrType.LONG: (-(2 ** 63), 2 ** 63 - 1),
}

# constant sets larger than this collapse to unknown (lattice height cap)
MAX_CONSTS = 16

# joins into one (stream, attr) slot before a still-growing bound widens
WIDEN_AFTER = 3

# absolute fixpoint round cap — the widening proof makes this unreachable,
# but a bug must degrade to imprecise facts, not a hang
MAX_ROUNDS = 64

# windows that neither reorder CURRENT-event emission nor synthesize
# values: facts flow through; monotone survives (CURRENT output only)
_ORDER_PRESERVING_WINDOWS = {
    "length", "time", "timelength", "externaltime",
    "lengthbatch", "timebatch", "externaltimebatch",
}

_EXTERNAL_TIME_WINDOWS = {"externaltime", "externaltimebatch"}


# ---------------------------------------------------------------------------
# the domain
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ValueFact:
    """Abstract value of one attribute. The default instance is TOP."""

    lo: Optional[int] = None
    hi: Optional[int] = None
    consts: Optional[frozenset] = None
    card: Optional[int] = None
    nullable: bool = True
    monotone: bool = False
    atype: Optional[AttrType] = None

    def is_top(self) -> bool:
        return (
            self.lo is None and self.hi is None and self.consts is None
            and self.card is None and self.nullable and not self.monotone
        )

    def contradiction(self) -> bool:
        """An empty domain: no concrete value satisfies the facts."""
        if self.lo is not None and self.hi is not None and self.lo > self.hi:
            return True
        return self.consts is not None and not self.consts

    def to_dict(self) -> dict:
        """JSON form for the plan `domains` section; TOP fields omitted."""
        out: dict = {}
        if self.lo is not None or self.hi is not None:
            out["interval"] = [self.lo, self.hi]
        if self.consts is not None:
            out["consts"] = sorted(self.consts, key=lambda v: (str(type(v)), v))
        if self.card is not None:
            out["card"] = self.card
        if not self.nullable:
            out["non_null"] = True
        if self.monotone:
            out["monotone"] = True
        return out


TOP = ValueFact()


def _min_open(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None or b is None:
        return None
    return min(a, b)


def _max_open(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None or b is None:
        return None
    return max(a, b)


def fact_join(a: ValueFact, b: ValueFact) -> ValueFact:
    """Least upper bound: the result over-approximates both inputs."""
    consts = None
    if a.consts is not None and b.consts is not None:
        u = a.consts | b.consts
        consts = u if len(u) <= MAX_CONSTS else None
    card = None
    if consts is not None:
        card = len(consts)
    elif a.card is not None and b.card is not None:
        card = max(a.card, b.card)
    return ValueFact(
        lo=_min_open(a.lo, b.lo),
        hi=_max_open(a.hi, b.hi),
        consts=consts,
        card=card,
        nullable=a.nullable or b.nullable,
        monotone=a.monotone and b.monotone,
        atype=a.atype if a.atype is b.atype else None,
    )


def fact_widen(old: ValueFact, new: ValueFact) -> ValueFact:
    """Widening: any bound still moving after WIDEN_AFTER joins opens."""
    return dataclasses.replace(
        new,
        lo=None if (old.lo is None or new.lo is None or new.lo < old.lo)
        else new.lo,
        hi=None if (old.hi is None or new.hi is None or new.hi > old.hi)
        else new.hi,
        consts=new.consts if new.consts == old.consts else None,
        card=new.card if new.card == old.card else None,
    )


def _const_fact(c: Constant) -> ValueFact:
    v = c.value
    t = c.type
    if t in _INTEGRAL or (isinstance(v, int) and not isinstance(v, bool)):
        iv = int(v)
        return ValueFact(
            lo=iv, hi=iv, consts=frozenset({iv}), card=1, nullable=False,
            atype=t if t in _INTEGRAL else AttrType.LONG,
        )
    if t is AttrType.STRING and isinstance(v, str):
        return ValueFact(
            consts=frozenset({v}), card=1, nullable=False, atype=t
        )
    return ValueFact(nullable=False, atype=t)


# ---------------------------------------------------------------------------
# abstract expression evaluation
# ---------------------------------------------------------------------------

# env: ref -> {attr: ValueFact} (per query, after source resolution)


def _lookup(var: Variable, env: dict) -> ValueFact:
    if var.stream_id is not None:
        facts = env.get(var.stream_id)
        if facts is None:
            return TOP
        return facts.get(var.attribute, TOP)
    hits = [f for f in env.values() if var.attribute in f]
    if len(hits) == 1:
        return hits[0][var.attribute]
    return TOP


def _promote(a: Optional[AttrType], b: Optional[AttrType]) -> Optional[AttrType]:
    if a in _INTEGRAL and b in _INTEGRAL:
        return AttrType.LONG if AttrType.LONG in (a, b) else AttrType.INT
    return None  # float/unknown arithmetic carries no integer bounds


def _arith_bounds(op: str, a: ValueFact, b: ValueFact):
    """Exact interval arithmetic for +, -, *; None bounds poison."""
    if None in (a.lo, a.hi, b.lo, b.hi):
        return None, None
    if op == "+":
        return a.lo + b.lo, a.hi + b.hi
    if op == "-":
        return a.lo - b.hi, a.hi - b.lo
    prods = [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi]
    return min(prods), max(prods)


class _Evaluator:
    """One query's abstract transfer: expression evaluation + predicate
    narrowing, collecting lint sites and rewrite notes along the way."""

    def __init__(self, qid: str, collect: bool = False):
        self.qid = qid
        self.collect = collect  # final pass: record lints/rewrites
        self.lints: list = []  # (code, message, node)
        self.decided: list = []  # (truth, node, label) per decided compare

    # -- expressions --------------------------------------------------------

    def eval(self, expr: Expression, env: dict) -> ValueFact:
        if isinstance(expr, Constant):
            return _const_fact(expr)
        if isinstance(expr, Variable):
            return _lookup(expr, env)
        if isinstance(expr, (Add, Subtract, Multiply)):
            return self._eval_arith(expr, env)
        if isinstance(expr, (Divide, Mod)):
            return self._eval_div(expr, env)
        if isinstance(expr, AttributeFunction):
            return self._eval_function(expr, env)
        if isinstance(expr, (Compare, And, Or, Not, IsNull, In)):
            truth = self.truth(expr, env)
            if truth is None:
                return ValueFact(atype=AttrType.BOOL)
            return ValueFact(
                consts=frozenset({truth}), card=1, nullable=False,
                atype=AttrType.BOOL,
            )
        return TOP

    def _eval_arith(self, expr, env: dict) -> ValueFact:
        a = self.eval(expr.left, env)
        b = self.eval(expr.right, env)
        t = _promote(a.atype, b.atype)
        op = {Add: "+", Subtract: "-", Multiply: "*"}[type(expr)]
        lo = hi = None
        if t is not None:
            lo, hi = _arith_bounds(op, a, b)
            bounds = TYPE_BOUNDS[t]
            if lo is not None and (lo < bounds[0] or hi > bounds[1]):
                self._lint(
                    "SA137",
                    f"'{_expr_str(expr)}' can overflow {t.name.lower()}: the "
                    f"proven operand domains give [{lo}, {hi}], outside "
                    f"[{bounds[0]}, {bounds[1]}]",
                    expr,
                )
                lo = hi = None
        mono = False
        if op in ("+", "-"):
            # monotone +/- a single constant keeps order
            mono = (a.monotone and b.lo is not None and b.lo == b.hi) or (
                op == "+" and b.monotone and a.lo is not None and a.lo == a.hi
            )
        elif op == "*":
            mono = (a.monotone and b.lo is not None and b.lo == b.hi
                    and b.lo > 0) or (
                b.monotone and a.lo is not None and a.lo == a.hi and a.lo > 0
            )
        return ValueFact(
            lo=lo, hi=hi, nullable=a.nullable or b.nullable,
            monotone=mono, atype=t,
        )

    def _eval_div(self, expr, env: dict) -> ValueFact:
        a = self.eval(expr.left, env)
        b = self.eval(expr.right, env)
        zero = False
        if b.consts is not None and 0 in b.consts:
            zero = True
        elif b.lo is not None and b.hi is not None and b.lo <= 0 <= b.hi:
            zero = True
        if zero:
            kind = "modulo" if isinstance(expr, Mod) else "division"
            self._lint(
                "SA137",
                f"'{_expr_str(expr)}': {kind} by zero is possible — the "
                "divisor's proven domain contains 0",
                expr,
            )
        return ValueFact(
            nullable=a.nullable or b.nullable,
            atype=_promote(a.atype, b.atype),
        )

    def _eval_function(self, expr: AttributeFunction, env: dict) -> ValueFact:
        from siddhi_tpu.core.executor import AGGREGATOR_NAMES

        low = expr.name.lower()
        if expr.namespace is None and expr.name in AGGREGATOR_NAMES:
            if low == "count":
                return ValueFact(lo=0, nullable=False, atype=AttrType.LONG)
            if low in ("min", "max", "minforever", "maxforever") \
                    and expr.parameters:
                arg = self.eval(expr.parameters[0], env)
                # extrema stay inside the argument's domain but lose
                # order/cardinality facts (window expiry can re-raise min)
                return ValueFact(
                    lo=arg.lo, hi=arg.hi, nullable=arg.nullable,
                    atype=arg.atype,
                )
            return TOP
        if expr.namespace is None and low == "coalesce" and expr.parameters:
            out = self.eval(expr.parameters[0], env)
            for p in expr.parameters[1:]:
                out = fact_join(out, self.eval(p, env))
            return dataclasses.replace(
                out,
                nullable=all(
                    self.eval(p, env).nullable for p in expr.parameters
                ),
            )
        return TOP

    # -- predicates ---------------------------------------------------------

    def truth(self, expr: Expression, env: dict) -> Optional[bool]:
        """3-valued abstract truth of a boolean expression."""
        if isinstance(expr, And):
            lt = self.truth(expr.left, env)
            rt = self.truth(expr.right, env)
            if lt is False or rt is False:
                return False
            if lt is True and rt is True:
                return True
            return None
        if isinstance(expr, Or):
            lt = self.truth(expr.left, env)
            rt = self.truth(expr.right, env)
            if lt is True or rt is True:
                return True
            if lt is False and rt is False:
                return False
            return None
        if isinstance(expr, Not):
            t = self.truth(expr.expression, env)
            return None if t is None else not t
        if isinstance(expr, Compare):
            return self._compare_truth(expr, env)
        if isinstance(expr, IsNull):
            if expr.expression is not None \
                    and not self.eval(expr.expression, env).nullable:
                return False
            return None
        if isinstance(expr, Constant) and isinstance(expr.value, bool):
            return bool(expr.value)
        return None

    def _compare_truth(self, cmp: Compare, env: dict) -> Optional[bool]:
        a = self.eval(cmp.left, env)
        b = self.eval(cmp.right, env)
        op = cmp.op
        if a.consts is not None and b.consts is not None:
            if op is CompareOp.EQ and not (a.consts & b.consts):
                return False
            if op is CompareOp.NEQ and not (a.consts & b.consts):
                return True
            if len(a.consts) == 1 and len(b.consts) == 1:
                av, bv = next(iter(a.consts)), next(iter(b.consts))
                if type(av) is type(bv):
                    return {
                        CompareOp.EQ: av == bv, CompareOp.NEQ: av != bv,
                        CompareOp.LT: av < bv, CompareOp.LE: av <= bv,
                        CompareOp.GT: av > bv, CompareOp.GE: av >= bv,
                    }[op]
        # interval separation (integer domains only)
        if op in (CompareOp.LT, CompareOp.LE):
            if a.hi is not None and b.lo is not None and (
                a.hi < b.lo or (op is CompareOp.LE and a.hi == b.lo)
            ):
                return True
            if a.lo is not None and b.hi is not None and (
                a.lo > b.hi or (op is CompareOp.LT and a.lo == b.hi)
            ):
                return False
        if op in (CompareOp.GT, CompareOp.GE):
            inv = CompareOp.LT if op is CompareOp.GT else CompareOp.LE
            t = self._compare_truth(
                Compare(left=cmp.right, op=inv, right=cmp.left), env
            )
            return t
        if op is CompareOp.EQ:
            if a.lo is not None and b.hi is not None and a.lo > b.hi:
                return False
            if a.hi is not None and b.lo is not None and a.hi < b.lo:
                return False
        if op is CompareOp.NEQ:
            eq = self._compare_truth(
                Compare(left=cmp.left, op=CompareOp.EQ, right=cmp.right), env
            )
            return None if eq is None else not eq
        return None

    def narrow(self, pred: Expression, env: dict) -> tuple[dict, Optional[bool]]:
        """(narrowed env, abstract truth) of `pred` holding over `env`.
        Decided leaf comparisons are recorded for SA136/rewrites."""
        if isinstance(pred, And):
            env1, lt = self.narrow(pred.left, env)
            env2, rt = self.narrow(pred.right, env1)
            if lt is False or rt is False:
                return env2, False
            return env2, (True if lt is True and rt is True else None)
        if isinstance(pred, Or):
            envl, lt = self.narrow(pred.left, env)
            envr, rt = self.narrow(pred.right, env)
            if lt is True or rt is True:
                return env, True
            if lt is False and rt is False:
                return envl, False
            if lt is False:
                return envr, rt
            if rt is False:
                return envl, lt
            return _env_join(envl, envr), None
        if isinstance(pred, Not):
            inner = _negate(pred.expression)
            if inner is not None:
                return self.narrow(inner, env)
            t = self.truth(pred, env)
            return env, t
        if isinstance(pred, Compare):
            t = self._compare_truth(pred, env)
            if t is not None:
                self.decided.append((t, pred, _expr_str(pred)))
                return env, t
            return self._narrow_compare(pred, env), None
        t = self.truth(pred, env)
        return env, t

    def _narrow_compare(self, cmp: Compare, env: dict) -> dict:
        """Narrow `var <op> literal` (either side) into a fresh env."""
        var, op, c = None, cmp.op, None
        if isinstance(cmp.left, Variable) and isinstance(cmp.right, Constant):
            var, c = cmp.left, cmp.right
        elif isinstance(cmp.right, Variable) and isinstance(cmp.left, Constant):
            var, c = cmp.right, cmp.left
            op = {
                CompareOp.LT: CompareOp.GT, CompareOp.LE: CompareOp.GE,
                CompareOp.GT: CompareOp.LT, CompareOp.GE: CompareOp.LE,
            }.get(op, op)
        if var is None:
            return env
        ref = _resolve_ref(var, env)
        if ref is None:
            return env
        old = env[ref].get(var.attribute, TOP)
        new = _narrow_fact(old, op, c)
        if new is old:
            return env
        env = dict(env)
        env[ref] = dict(env[ref])
        env[ref][var.attribute] = new
        return env

    def _lint(self, code: str, message: str, node) -> None:
        if self.collect:
            self.lints.append((code, message, node))


def _resolve_ref(var: Variable, env: dict) -> Optional[str]:
    if var.stream_id is not None:
        return var.stream_id if var.stream_id in env else None
    hits = [ref for ref, facts in env.items() if var.attribute in facts]
    return hits[0] if len(hits) == 1 else None


def _narrow_fact(old: ValueFact, op: CompareOp, c: Constant) -> ValueFact:
    v = c.value
    if isinstance(v, str):
        if op is CompareOp.EQ:
            consts = (
                old.consts & {v} if old.consts is not None else frozenset({v})
            )
            return dataclasses.replace(
                old, consts=consts, card=len(consts), nullable=False
            )
        if op is CompareOp.NEQ and old.consts is not None:
            consts = old.consts - {v}
            return dataclasses.replace(old, consts=consts, card=len(consts))
        return old
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        return old
    if old.atype not in _INTEGRAL:
        # float/unknown domains carry no integer intervals; the exclusive-
        # bound rounding below would be unsound there (price > 10.0 does
        # NOT imply price >= 11). A passing comparison still proves
        # non-null.
        return old if not old.nullable else dataclasses.replace(
            old, nullable=False
        )
    iv = int(v)
    lo, hi, consts = old.lo, old.hi, old.consts
    if op is CompareOp.EQ:
        lo = iv if lo is None else max(lo, iv)
        hi = iv if hi is None else min(hi, iv)
        consts = (
            consts & {iv} if consts is not None else frozenset({iv})
        )
        return dataclasses.replace(
            old, lo=lo, hi=hi, consts=consts, card=len(consts),
            nullable=False,
        )
    if op is CompareOp.NEQ:
        if consts is not None:
            consts = consts - {iv}
            return dataclasses.replace(old, consts=consts, card=len(consts))
        return old
    # order comparisons: integer narrowing (float literals round safely
    # toward the retained side)
    if op is CompareOp.GT:
        bound = int(v) + 1 if float(v).is_integer() else int(-(-v // 1))
        lo = bound if lo is None else max(lo, bound)
    elif op is CompareOp.GE:
        bound = int(-(-v // 1))
        lo = bound if lo is None else max(lo, bound)
    elif op is CompareOp.LT:
        bound = int(v) - 1 if float(v).is_integer() else int(v // 1)
        hi = bound if hi is None else min(hi, bound)
    elif op is CompareOp.LE:
        bound = int(v // 1)
        hi = bound if hi is None else min(hi, bound)
    if consts is not None:
        kept = frozenset(
            x for x in consts
            if isinstance(x, int)
            and (lo is None or x >= lo) and (hi is None or x <= hi)
        )
    else:
        kept = None
    return dataclasses.replace(
        old, lo=lo, hi=hi, consts=kept,
        card=len(kept) if kept is not None else old.card, nullable=False,
    )


def _negate(expr: Expression) -> Optional[Expression]:
    """Push a NOT one level down (De Morgan / comparison flip)."""
    if isinstance(expr, Compare):
        flip = {
            CompareOp.LT: CompareOp.GE, CompareOp.LE: CompareOp.GT,
            CompareOp.GT: CompareOp.LE, CompareOp.GE: CompareOp.LT,
            CompareOp.EQ: CompareOp.NEQ, CompareOp.NEQ: CompareOp.EQ,
        }
        return Compare(left=expr.left, op=flip[expr.op], right=expr.right)
    if isinstance(expr, And):
        left, right = _negate(expr.left), _negate(expr.right)
        if left is not None and right is not None:
            return Or(left=left, right=right)
    if isinstance(expr, Or):
        left, right = _negate(expr.left), _negate(expr.right)
        if left is not None and right is not None:
            return And(left=left, right=right)
    if isinstance(expr, Not):
        return expr.expression
    return None


def _env_join(a: dict, b: dict) -> dict:
    out: dict = {}
    for ref in a:
        if ref not in b:
            out[ref] = a[ref]
            continue
        fa, fb = a[ref], b[ref]
        merged = {}
        for attr in fa:
            if attr in fb:
                merged[attr] = fact_join(fa[attr], fb[attr])
            else:
                merged[attr] = fa[attr]
        for attr in fb:
            merged.setdefault(attr, fb[attr])
        out[ref] = merged
    for ref in b:
        out.setdefault(ref, b[ref])
    return out


def _expr_str(expr: Expression) -> str:
    """Compact deterministic rendering for rewrite notes and lint text."""
    if isinstance(expr, Constant):
        return repr(expr.value) if isinstance(expr.value, str) else str(
            expr.value
        )
    if isinstance(expr, Variable):
        return (
            f"{expr.stream_id}.{expr.attribute}" if expr.stream_id
            else expr.attribute
        )
    if isinstance(expr, Compare):
        return (
            f"{_expr_str(expr.left)} {expr.op.value} {_expr_str(expr.right)}"
        )
    if isinstance(expr, And):
        return f"({_expr_str(expr.left)} and {_expr_str(expr.right)})"
    if isinstance(expr, Or):
        return f"({_expr_str(expr.left)} or {_expr_str(expr.right)})"
    if isinstance(expr, Not):
        return f"not {_expr_str(expr.expression)}"
    ops = {Add: "+", Subtract: "-", Multiply: "*", Divide: "/", Mod: "%"}
    for cls, sym in ops.items():
        if isinstance(expr, cls):
            def side(e):
                s = _expr_str(e)
                return f"({s})" if isinstance(e, tuple(ops)) else s
            return f"{side(expr.left)} {sym} {side(expr.right)}"
    if isinstance(expr, AttributeFunction):
        args = ", ".join(_expr_str(p) for p in expr.parameters)
        ns = f"{expr.namespace}:" if expr.namespace else ""
        return f"{ns}{expr.name}({args})"
    if isinstance(expr, IsNull):
        inner = (
            _expr_str(expr.expression) if expr.expression is not None
            else str(expr.stream_id)
        )
        return f"{inner} is null"
    return type(expr).__name__


# ---------------------------------------------------------------------------
# the analysis result
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ValueAnalysis:
    """Fixpoint facts + their consumers' inputs (rewrites, lints)."""

    # stream id -> {attr: ValueFact} — facts at stream ingress
    stream_facts: dict = dataclasses.field(default_factory=dict)
    # advisory rewrite notes, deterministic order (plan v3 `rewrites`)
    rewrites: list = dataclasses.field(default_factory=list)
    # (code, message, line, col, qid) for SA135-SA137
    lint_sites: list = dataclasses.field(default_factory=list)
    # stream id -> attrs no consumer reads (mirrors ingest _compute_keep)
    dead_columns: dict = dataclasses.field(default_factory=dict)
    # declared-hint lanes inference could NOT independently prove
    unprovable: list = dataclasses.field(default_factory=list)
    rounds: int = 0
    widened: list = dataclasses.field(default_factory=list)

    def facts_for(self, sid: str) -> dict:
        return self.stream_facts.get(sid, {})

    def domains_dict(self) -> dict:
        """{sid: {attr: fact-dict}} with TOP entries omitted; sorted."""
        out: dict = {}
        for sid in sorted(self.stream_facts):
            entries = {
                attr: fact.to_dict()
                for attr, fact in sorted(self.stream_facts[sid].items())
                if not fact.is_top()
            }
            if entries:
                out[sid] = entries
        return out


def _iter_entries(app: SiddhiApp):
    """(qid, query, partition | None) in execution order — the one shared
    id walk (query_api assign_execution_ids), like cost.iter_query_entries
    but keeping the owning partition for inner-stream scoping."""
    from siddhi_tpu.query_api.execution import assign_execution_ids

    for ent in assign_execution_ids(app):
        if ent[0] == "query":
            yield ent[1], ent[2], None
        else:
            for qid, q in ent[3]:
                yield qid, q, ent[1]


def _inner_key(pid: Optional[str], name: str) -> str:
    return f"{pid or '?'}::#{name}"


def analyze_values(app: SiddhiApp, sym=None) -> ValueAnalysis:
    """Run the abstract interpretation to a fixpoint. Pure and total:
    semantically-bad apps degrade to TOP facts, never exceptions."""
    from siddhi_tpu.analysis.symbols import build_symbols
    from siddhi_tpu.core.wire import parse_wire_hints

    if sym is None:
        sym = build_symbols(app, [])
    va = ValueAnalysis()

    hints = parse_wire_hints(find_annotation(app.annotations, "app:wire"))
    entries = list(_iter_entries(app))

    # ---- seed: declared streams start from their external contribution
    for sid, schema in sym.streams.items():
        if schema is None or sid.startswith("!"):
            continue
        facts = {}
        for attr, t in schema.items():
            fact = ValueFact(atype=t)
            hint = hints.get((sid, attr))
            if hint is not None and t is not None:
                if hint[0] == "range" and t in _INTEGRAL:
                    fact = dataclasses.replace(
                        fact, lo=int(hint[1]), hi=int(hint[2])
                    )
                elif hint[0] == "dict":
                    fact = dataclasses.replace(fact, card=int(hint[1]))
                elif hint[0] == "delta" and t in _INTEGRAL:
                    fact = dataclasses.replace(fact, monotone=True)
            facts[attr] = fact
        va.stream_facts[sid] = facts

    # ---- seed: the event-time contract — a LONG/INT attribute consumed as
    # the time attribute of an external-time window is the stream's event
    # clock, ordered by contract (PR 14's reorder stage enforces exactly
    # this); a wrong assumption costs one misfit rebuild, never wrong bytes
    for _qid, q, _part in entries:
        for src in _query_sources(q):
            if src.is_inner or src.is_fault:
                continue
            facts = va.stream_facts.get(src.stream_id)
            if facts is None:
                continue
            for h in src.handlers:
                if not isinstance(h, WindowHandler):
                    continue
                w = h.window
                key = (
                    w.name.lower() if w.namespace is None
                    else f"{w.namespace}:{w.name}".lower()
                )
                if key not in _EXTERNAL_TIME_WINDOWS or not w.parameters:
                    continue
                p0 = w.parameters[0]
                if isinstance(p0, Variable) and p0.attribute in facts \
                        and facts[p0.attribute].atype in _INTEGRAL:
                    facts[p0.attribute] = dataclasses.replace(
                        facts[p0.attribute], monotone=True
                    )

    declared = set(va.stream_facts)

    # ---- fixpoint over the insert-into graph
    join_counts: dict = {}
    for round_no in range(1, MAX_ROUNDS + 1):
        va.rounds = round_no
        changed = False
        for qid, q, part in entries:
            target, out_facts = _transfer(q, qid, part, sym, va, declared)
            if target is None or out_facts is None:
                continue
            old = va.stream_facts.get(target)
            if old is None:
                va.stream_facts[target] = dict(out_facts)
                changed = True
                continue
            for attr, fact in out_facts.items():
                prev = old.get(attr)
                if prev is None:
                    old[attr] = fact
                    changed = True
                    continue
                new = fact_join(prev, fact)
                if new == prev:
                    continue
                key = (target, attr)
                join_counts[key] = join_counts.get(key, 0) + 1
                if join_counts[key] > WIDEN_AFTER:
                    new = fact_widen(prev, new)
                    if key not in va.widened:
                        va.widened.append(key)
                if new != prev:
                    old[attr] = new
                    changed = True
        if not changed:
            break

    # ---- final pass: lints + rewrites against the stable facts
    _collect_notes(app, sym, va, entries, declared)
    _collect_dead_columns(app, sym, va, entries)
    _check_declared_agreement(sym, va, hints)
    return va


def _query_sources(q: Query):
    stream = q.input_stream
    if isinstance(stream, SingleInputStream):
        return [stream]
    if isinstance(stream, JoinInputStream):
        return [stream.left, stream.right]
    if isinstance(stream, StateInputStream):
        return list(iter_state_streams(stream.state))
    return []


def _source_env_entry(
    src: SingleInputStream, part, sym, va: ValueAnalysis, ev: _Evaluator
) -> tuple[Optional[dict], Optional[bool]]:
    """(facts after this source's handler chain, filter truth). None facts
    = unknown source (table/window/aggregation/open schema): skip."""
    sid = src.stream_id
    if src.is_fault:
        return None, None
    if src.is_inner:
        facts = va.stream_facts.get(_inner_key(part_id(part), sid))
    elif sid in va.stream_facts:
        facts = va.stream_facts[sid]
    elif sid in sym.streams or sid in sym.windows:
        return None, None  # open schema / named window: no facts
    else:
        facts = va.stream_facts.get(sid)  # insert-into-only stream
    if facts is None:
        return None, None
    env = {src.ref: dict(facts)}
    truth: Optional[bool] = None
    for h in src.handlers:
        if isinstance(h, Filter):
            env, t = ev.narrow(h.expression, env)
            if t is False:
                truth = False
            elif truth is None and t is not None:
                truth = t if truth is None else truth
        elif isinstance(h, WindowHandler):
            w = h.window
            key = (
                w.name.lower() if w.namespace is None
                else f"{w.namespace}:{w.name}".lower()
            )
            if key not in _ORDER_PRESERVING_WINDOWS:
                env = {
                    src.ref: {
                        a: dataclasses.replace(f, monotone=False)
                        for a, f in env[src.ref].items()
                    }
                }
        elif isinstance(h, StreamFunctionHandler):
            return None, truth  # schema may change: facts unknown
    return env.get(src.ref), truth


def part_id(part) -> Optional[str]:
    # `part` is already the pid string assign_execution_ids handed out
    return part


def _transfer(
    q: Query, qid: str, part, sym, va: ValueAnalysis, declared: set,
    ev: Optional[_Evaluator] = None,
):
    """(target stream key, output facts) for one query under the current
    stream facts; (None, None) when the query writes no stream or its
    sources are unknown."""
    out_stream = q.output_stream
    target = getattr(out_stream, "target", None)
    if ev is None:
        ev = _Evaluator(qid)
    env: dict = {}
    mono_ok = isinstance(q.input_stream, SingleInputStream)
    for src in _query_sources(q):
        facts, _t = _source_env_entry(src, part, sym, va, ev)
        if facts is None:
            env[src.ref] = {}
        else:
            env[src.ref] = facts
    if not isinstance(q.input_stream, SingleInputStream):
        # joins/patterns: per-side domains survive, order does not
        env = {
            ref: {
                a: dataclasses.replace(f, monotone=False)
                for a, f in facts.items()
            }
            for ref, facts in env.items()
        }

    sel = q.selector
    if sel.group_by or sel.order_by:
        mono_ok = False
    if getattr(out_stream, "output_events", None) in (
        OutputEventsFor.EXPIRED, OutputEventsFor.ALL
    ):
        mono_ok = False

    out_facts: dict = {}
    if sel.select_all or not sel.selection_list:
        for facts in env.values():
            for attr, fact in facts.items():
                out_facts[attr] = fact
    else:
        for oa in sel.selection_list:
            try:
                name = oa.name
            except ValueError:
                continue
            has_agg = _has_aggregator(oa.expression)
            fact = ev.eval(oa.expression, env)
            if has_agg and not isinstance(oa.expression, AttributeFunction):
                fact = dataclasses.replace(fact, lo=None, hi=None,
                                           consts=None, card=None)
            out_facts[name] = fact
    if not mono_ok:
        out_facts = {
            a: dataclasses.replace(f, monotone=False)
            for a, f in out_facts.items()
        }
    if sel.having is not None:
        henv, _t = ev.narrow(sel.having, {None: out_facts})
        out_facts = henv.get(None, out_facts)

    if not target:
        return None, None
    if target.startswith("!"):
        return None, None
    if target in sym.tables or target in sym.windows \
            or target in sym.aggregations:
        return None, None
    if getattr(out_stream, "is_inner", False):
        return _inner_key(part_id(part), target), out_facts
    if target in declared:
        # declared target: external senders already contribute TOP/contract
        # facts — join the producer's contribution into that floor
        return target, out_facts
    return target, out_facts


def _has_aggregator(expr: Expression) -> bool:
    from siddhi_tpu.core.executor import AGGREGATOR_NAMES

    if isinstance(expr, AttributeFunction):
        if expr.namespace is None and expr.name in AGGREGATOR_NAMES:
            return True
        return any(_has_aggregator(p) for p in expr.parameters)
    for child in ("left", "right", "expression"):
        c = getattr(expr, child, None)
        if isinstance(c, Expression) and _has_aggregator(c):
            return True
    return False


# ---------------------------------------------------------------------------
# consumers: lints + rewrites (final pass over stable facts)
# ---------------------------------------------------------------------------


def _collect_notes(
    app: SiddhiApp, sym, va: ValueAnalysis, entries, declared: set
) -> None:
    for qid, q, part in entries:
        ev = _Evaluator(qid, collect=True)
        for src in _query_sources(q):
            sid = src.stream_id
            if src.is_inner:
                facts = va.stream_facts.get(_inner_key(part_id(part), sid))
            else:
                facts = va.stream_facts.get(sid)
            if facts is None:
                continue
            env = {src.ref: dict(facts)}
            for h in src.handlers:
                if not isinstance(h, Filter):
                    continue
                ev.decided = []
                env, truth = ev.narrow(h.expression, env)
                node = h.expression
                if truth is False:
                    va.lint_sites.append((
                        "SA135",
                        f"filter '{_expr_str(node)}' on stream '{sid}' is "
                        "provably false on the proven value domain: the "
                        "query can never emit",
                        getattr(node, "line", None),
                        getattr(node, "col", None), qid,
                    ))
                    va.rewrites.append({
                        "kind": "unreachable-filter", "query": qid,
                        "stream": sid, "filter": _expr_str(node),
                    })
                    continue
                for t, cnode, label in ev.decided:
                    va.lint_sites.append((
                        "SA136",
                        f"comparison '{label}' is always "
                        f"{'true' if t else 'false'} on the proven value "
                        "domain",
                        getattr(cnode, "line", None),
                        getattr(cnode, "col", None), qid,
                    ))
                    if t:
                        va.rewrites.append({
                            "kind": "drop-true-conjunct", "query": qid,
                            "stream": sid, "conjunct": label,
                        })
        # selector: const folds + overflow lints over the full source env
        env = {}
        for src in _query_sources(q):
            facts, _t = _source_env_entry(src, part, sym, va, ev)
            env[src.ref] = facts if facts is not None else {}
        for oa in q.selector.selection_list:
            try:
                name = oa.name
            except ValueError:
                continue
            if _has_aggregator(oa.expression):
                continue
            fact = ev.eval(oa.expression, env)
            if not isinstance(oa.expression, (Constant, Variable)) \
                    and fact.consts is not None and len(fact.consts) == 1:
                va.rewrites.append({
                    "kind": "const-fold", "query": qid, "attr": name,
                    "expr": _expr_str(oa.expression),
                    "value": next(iter(fact.consts)),
                })
        if q.selector.having is not None:
            ev.decided = []
            _env2, truth = ev.narrow(q.selector.having, {None: {}})
        for code, message, node in ev.lints:
            va.lint_sites.append((
                code, message,
                getattr(node, "line", None), getattr(node, "col", None),
                qid,
            ))
    va.lint_sites.sort(
        key=lambda s: (s[4] or "", s[0], s[2] or 0, s[3] or 0, s[1])
    )


def _iter_query_exprs(q: Query):
    """Every expression a query evaluates, source refs included."""
    for src in _query_sources(q):
        for h in src.handlers:
            if isinstance(h, Filter):
                yield h.expression
            elif isinstance(h, WindowHandler):
                yield from h.window.parameters
            elif isinstance(h, StreamFunctionHandler):
                yield from h.parameters
    stream = q.input_stream
    if isinstance(stream, JoinInputStream):
        if stream.on is not None:
            yield stream.on
        if stream.within is not None:
            yield stream.within
        if stream.per is not None:
            yield stream.per
    sel = q.selector
    for oa in sel.selection_list:
        yield oa.expression
    yield from sel.group_by
    if sel.having is not None:
        yield sel.having
    for ob in sel.order_by:
        yield ob.variable


def _mark_used(expr: Expression, by_ref: dict, used: dict) -> None:
    if isinstance(expr, Variable):
        if expr.stream_id is not None:
            sid = by_ref.get(expr.stream_id, expr.stream_id)
            used.setdefault(sid, set()).add(expr.attribute)
        else:
            for sid in by_ref.values():
                used.setdefault(sid, set()).add(expr.attribute)
        return
    if isinstance(expr, AttributeFunction):
        for p in expr.parameters:
            _mark_used(p, by_ref, used)
        return
    for child in ("left", "right", "expression"):
        c = getattr(expr, child, None)
        if isinstance(c, Expression):
            _mark_used(c, by_ref, used)


def _collect_dead_columns(app: SiddhiApp, sym, va, entries) -> None:
    """Per consumed outer stream: attributes NO consumer reads — the
    static mirror of the fused ingest's projected wire (`_compute_keep`),
    surfaced as plan rewrites so the pruning is visible pre-runtime."""
    used: dict = {}
    consumed: set = set()
    keep_all: set = set(sym.sinked)
    for _qid, q, _part in entries:
        by_ref = {}
        for src in _query_sources(q):
            if src.is_inner or src.is_fault:
                continue
            by_ref[src.ref] = src.stream_id
            consumed.add(src.stream_id)
            if q.selector.select_all or not q.selector.selection_list:
                keep_all.add(src.stream_id)
        for expr in _iter_query_exprs(q):
            _mark_used(expr, by_ref, used)
    for elem in app.execution_elements:
        for pt in getattr(elem, "partition_types", []) or []:
            consumed.add(pt.stream_id)
            expr = getattr(pt, "expression", None)
            if expr is not None:
                _mark_used(expr, {pt.stream_id: pt.stream_id}, used)
            for rng in getattr(pt, "ranges", []) or []:
                _mark_used(
                    rng.condition, {pt.stream_id: pt.stream_id}, used
                )
    for ad in app.aggregation_definitions.values():
        sid = getattr(getattr(ad, "input", None), "stream_id", None)
        if sid is not None:
            keep_all.add(sid)
    for sid in sorted(consumed):
        schema = sym.streams.get(sid)
        if not schema or sid in keep_all:
            continue
        dead = [a for a in schema if a not in used.get(sid, set())]
        if dead:
            va.dead_columns[sid] = dead
            va.rewrites.append({
                "kind": "prune-dead-columns", "stream": sid,
                "columns": dead,
            })


def _check_declared_agreement(sym, va: ValueAnalysis, hints: dict) -> None:
    """Every declared `@app:wire` lane must come back from inference at
    least as narrow (it is seeded from the contract, so normally it does)
    or be recorded as explicitly unprovable — the agreement contract the
    sweep test asserts."""
    inferred = infer_wire_hints(va, sym)
    for (sid, col), hint in sorted(hints.items()):
        got = inferred.get((sid, col))
        if got is None:
            va.unprovable.append({
                "stream": sid, "attr": col, "declared": hint[0],
                "reason": "no fact survives at this lane (open schema or "
                          "unknown column)",
            })


# ---------------------------------------------------------------------------
# inferred wire hints
# ---------------------------------------------------------------------------


def infer_wire_hints(va: ValueAnalysis, sym) -> dict:
    """(stream_id, attr) -> hint tuple in `parse_wire_hints` format, from
    the proven facts: monotone -> delta int16 (the same default a declared
    `delta='true'` picks), small constant set / cardinality bound -> dict,
    bounded interval -> range. One entry per lane, preferring the
    strongest encoder; `build_wire_spec` applies declared hints first and
    drops anything that does not undercut the wide lane."""
    import numpy as np

    out: dict = {}
    for sid in sorted(va.stream_facts):
        if "::#" in sid:
            continue  # partition-inner streams have no junction wire
        facts = va.stream_facts[sid]
        for attr in facts:
            fact = facts[attr]
            t = fact.atype
            if t is None:
                continue
            if fact.monotone and t in _INTEGRAL:
                out[(sid, attr)] = ("delta", np.dtype(np.int16))
                continue
            card = fact.card
            if fact.consts is not None:
                card = len(fact.consts)
            if card is not None and 1 <= card <= 65536 \
                    and t in _INTEGRAL + _INTERNED:
                out[(sid, attr)] = ("dict", max(2, card))
                continue
            if t in _INTEGRAL and fact.lo is not None \
                    and fact.hi is not None:
                out[(sid, attr)] = ("range", fact.lo, fact.hi)
    return out


def infer_wire_hints_for_app(app: SiddhiApp, sym=None) -> dict:
    """One-call form for the runtime (`app_runtime._rebuild_fused_ingest`):
    never raises — inference failure means no overlay, not no wire."""
    try:
        from siddhi_tpu.analysis.symbols import build_symbols

        if sym is None:
            sym = build_symbols(app, [])
        return infer_wire_hints(analyze_values(app, sym), sym)
    except Exception:
        import logging

        logging.getLogger(__name__).warning(
            "wire inference failed for app '%s'; declared hints only",
            getattr(app, "name", "?"), exc_info=True,
        )
        return {}


def filter_selectivity(pred: Expression, facts: dict) -> Optional[float]:
    """Interval-overlap refinement of a filter's static selectivity for
    the cost model (analysis/cost.py): the fraction of each attribute's
    PROVEN domain the predicate retains, under a uniform-distribution
    assumption, multiplied across narrowed attributes and clamped to
    [0.01, 1.0] (0.0 exactly when the filter is provably false). Returns
    None when no bounded domain narrows — the flat per-operator default
    then stands."""
    ev = _Evaluator("sel")
    env = {"_s": dict(facts)}
    env2, truth = ev.narrow(pred, env)
    if truth is False:
        return 0.0
    if truth is True:
        return 1.0
    after = env2.get("_s", facts)
    ratio = 1.0
    narrowed = False
    for attr, f0 in facts.items():
        f1 = after.get(attr, f0)
        if f1 is f0:
            continue
        if f0.consts is not None and f1.consts is not None \
                and len(f1.consts) < len(f0.consts):
            narrowed = True
            ratio *= len(f1.consts) / len(f0.consts)
        elif f0.lo is not None and f0.hi is not None \
                and f1.lo is not None and f1.hi is not None \
                and (f1.lo, f1.hi) != (f0.lo, f0.hi):
            w0 = f0.hi - f0.lo + 1
            w1 = max(0, f1.hi - f1.lo + 1)
            if w0 > 0 and w1 < w0:
                narrowed = True
                ratio *= w1 / w0
    if not narrowed:
        return None
    return min(1.0, max(0.01, round(ratio, 4)))


# ---------------------------------------------------------------------------
# lint driver (SA135-SA137; SA138 rides cost._check_wire_dominance)
# ---------------------------------------------------------------------------


def check_values(app: SiddhiApp, sym, diags: list, va=None) -> "ValueAnalysis":
    """Emit the value-analysis lints; returns the analysis for reuse."""
    from siddhi_tpu.analysis.diagnostics import WARNING, Diagnostic

    if va is None:
        va = analyze_values(app, sym)
    for code, message, line, col, qid in va.lint_sites:
        diags.append(Diagnostic(
            code, message, line, col, severity=WARNING, query=qid,
        ))
    return va
