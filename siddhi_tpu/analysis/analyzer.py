"""Semantic analysis pass over a parsed `SiddhiApp`.

`analyze(app)` runs before (and independently of) runtime construction and
returns an `AnalysisResult` of `Diagnostic`s:

* name resolution — streams / tables / windows / aggregations / fault
  streams / join aliases / pattern state labels, undefined and duplicate
  references (SA1xx);
* type inference over `core/types.py` promotion rules — incompatible
  compares, arithmetic on STRING/BOOL, non-boolean filters, insert-into
  arity/type mismatches (SA2xx);
* window / stream-function / aggregator name + argument validation against
  the builtin tables and the extension registry (SA3xx);
* stream->query dataflow (dead streams, unfed windows, cycles — SA4xx,
  warnings).

The analyzer is deliberately *under*-approximate: anything it cannot know
statically (extension return types, schemas downstream of extension stream
functions) becomes "unknown" and related checks are skipped, so a clean
result is trustworthy and a reported error is near-certain to fail at
`create_runtime` or later.
"""

from __future__ import annotations

from typing import Optional

from siddhi_tpu.core.types import NUMERIC_TYPES, AttrType, promote
from siddhi_tpu.query_api.definition import AggregationDefinition, WindowDefinition
from siddhi_tpu.query_api.execution import (
    DeleteStream,
    Filter,
    InsertIntoStream,
    JoinInputStream,
    OrderByAttribute,
    Partition,
    Query,
    ReturnStream,
    Selector,
    SingleInputStream,
    StateInputStream,
    StreamFunctionHandler,
    UpdateOrInsertStream,
    UpdateStream,
    WindowHandler,
    iter_state_streams,
)
from siddhi_tpu.query_api.expression import Constant, Variable
from siddhi_tpu.query_api.annotation import find_annotation
from siddhi_tpu.query_api.siddhi_app import SiddhiApp

from siddhi_tpu.analysis.dataflow import QueryFlow, check_dataflow
from siddhi_tpu.analysis.diagnostics import (
    ERROR,
    WARNING,
    AnalysisResult,
    Diagnostic,
)
from siddhi_tpu.analysis.registries import check_stream_function, check_window
from siddhi_tpu.analysis.symbols import SymbolTable, build_symbols
from siddhi_tpu.analysis.typecheck import AnalysisScope, ExprChecker, _loc


def analyze(app: SiddhiApp) -> AnalysisResult:
    """Run the full semantic pass. Never raises on bad apps — every finding
    becomes a Diagnostic; an unexpected analyzer fault degrades to an SA000
    warning rather than masking runtime behavior. The returned result also
    carries the static `FusionPlan` (`result.fusion_plan`) built by the
    cost/fusion passes."""
    diags: list[Diagnostic] = []
    out: dict = {}
    try:
        _analyze(app, diags, out=out)
    except Exception as exc:  # pragma: no cover - analyzer defect guard
        diags.append(Diagnostic(
            "SA000",
            f"internal analyzer error, analysis incomplete: {exc!r}",
            severity=WARNING,
        ))
    result = AnalysisResult(diags, app_name=app.name)
    result.fusion_plan = out.get("fusion_plan")
    return result


def collect_flows(app: SiddhiApp) -> list[QueryFlow]:
    """The app's query-level dataflow edges (consumed stream ids ->
    produced stream id per query/aggregation), computed by the same pass
    `analyze()` runs. Never raises — the EXPLAIN plan builder
    (observability/explain.py) must render best-effort even for apps the
    analyzer would reject (e.g. invalid partition keys, SA115)."""
    diags: list[Diagnostic] = []
    try:
        return _analyze(app, diags, lints=False)
    except Exception:  # pragma: no cover - analyzer defect guard
        return []


def _analyze(
    app: SiddhiApp,
    diags: list[Diagnostic],
    out: Optional[dict] = None,
    lints: bool = True,
) -> list[QueryFlow]:
    sym = build_symbols(app, diags)
    flows: list[QueryFlow] = []

    for wd in app.window_definitions.values():
        _check_window_definition(wd, sym, diags)

    for ad in app.aggregation_definitions.values():
        _check_aggregation_definition(ad, sym, diags, flows)

    # query id assignment mirrors SiddhiAppRuntime.__init__: explicit @info
    # names are reserved app-wide, unnamed queries take the next free queryN
    taken: dict[str, int] = {}
    for elem in app.execution_elements:
        inner = [elem] if isinstance(elem, Query) else list(
            getattr(elem, "queries", []) or []
        )
        for q in inner:
            info = find_annotation(q.annotations, "info")
            name = info.element("name") if info else None
            if name:
                taken[name] = taken.get(name, 0) + 1
                if taken[name] == 2:  # report once per duplicated name
                    line, col = _loc(q)
                    diags.append(Diagnostic(
                        "SA105", f"duplicate query name '{name}'", line, col
                    ))

    # query/partition ids come from the ONE shared assignment the runtime
    # uses (query_api/execution.py assign_execution_ids) so diagnostics and
    # explain plans name exactly the queries the runtime would build
    from siddhi_tpu.query_api.execution import assign_execution_ids

    inferred_targets: dict[str, list] = {}
    for ent in assign_execution_ids(app):
        if ent[0] == "query":
            _kind, qid, q = ent
            _analyze_query(q, qid, sym, diags, inferred_targets, flows)
        else:
            _kind, pid, elem, inner_ids = ent
            _analyze_partition(
                elem, pid, sym, diags, inferred_targets, flows, inner_ids
            )

    check_dataflow(app, sym, flows, diags)

    if lints:
        # value analysis (SA135-SA137) feeds the cost model (narrowed
        # widths, interval selectivity), SA133/SA138, and the plan's
        # rewrites/domains sections; its own failure degrades to
        # no-facts, never to a failed analysis
        from siddhi_tpu.analysis.cost import check_costs
        from siddhi_tpu.analysis.fusion import check_fusion

        va = None
        try:
            from siddhi_tpu.analysis.values import check_values

            va = check_values(app, sym, diags)
        except Exception:  # pragma: no cover - analyzer defect guard
            va = None
        model = check_costs(app, sym, diags, values=va)
        plan = check_fusion(app, sym, diags, model, values=va)
        if out is not None:
            out["fusion_plan"] = plan
    return flows


# ---------------------------------------------------------------------------
# definitions
# ---------------------------------------------------------------------------


def _check_window_definition(
    wd: WindowDefinition, sym: SymbolTable, diags: list[Diagnostic]
) -> None:
    checker = ExprChecker(sym, diags)
    scope = AnalysisScope().add(wd.id, sym.windows.get(wd.id) or {})
    if wd.window is not None:
        check_window(wd.window, checker, scope, diags, None)


def _check_aggregation_definition(
    ad: AggregationDefinition,
    sym: SymbolTable,
    diags: list[Diagnostic],
    flows: list[QueryFlow],
) -> None:
    stream = ad.basic_single_input_stream
    if stream is None:
        return
    qid = f"aggregation '{ad.id}'"
    checker = ExprChecker(sym, diags, query=qid)
    schema = sym.streams.get(stream.stream_id)
    if stream.stream_id not in sym.streams:
        line, col = _loc(stream)
        diags.append(Diagnostic(
            "SA101",
            f"aggregation '{ad.id}': stream '{stream.stream_id}' is not defined",
            line, col, query=qid,
        ))
        return
    scope = AnalysisScope()
    ref = stream.alias or stream.stream_id
    scope.add(ref, dict(schema) if schema is not None else None)
    if ref != stream.stream_id:
        scope.add(stream.stream_id, dict(schema) if schema is not None else None)
    schema2 = _apply_handlers(stream, schema, ref, checker, scope, diags, qid)
    scope.refs[ref] = schema2
    # `aggregate by <attr>`: the bucket timestamp source must be INT/LONG
    # (runtime analog: AggregationRuntime raises 'aggregate by attribute
    # must be long' at creation, core/aggregation.py)
    if ad.aggregate_attribute is not None:
        t = checker.resolve_variable(ad.aggregate_attribute, scope)
        if t is not None and t not in (AttrType.INT, AttrType.LONG):
            line, col = _loc(ad.aggregate_attribute)
            diags.append(Diagnostic(
                "SA116",
                f"aggregation '{ad.id}': 'aggregate by "
                f"{ad.aggregate_attribute.attribute}' must be INT/LONG "
                f"(epoch millis), got {t!r}",
                line, col, query=qid,
            ))
    if ad.selector is not None:
        _analyze_selector(
            ad.selector, checker, scope,
            list(schema2.items()) if schema2 is not None else None,
        )
    flows.append(QueryFlow(qid, consumes={stream.stream_id}, produces=None))


# ---------------------------------------------------------------------------
# query inputs
# ---------------------------------------------------------------------------


def _inferred_schema(inferred_targets: Optional[dict], sid: str):
    """Schema of a stream defined implicitly by an earlier insert-into
    (mirrors _wire_insert registering the inferred StreamSchema). Returns
    (found, schema|None-open)."""
    if inferred_targets is None or sid not in inferred_targets:
        return False, None
    attrs = inferred_targets[sid]
    if any(n is None for n, _t in attrs):
        return True, None  # unnameable projection: stay open
    return True, {n: t for n, t in attrs}


def _resolve_single_source(
    s: SingleInputStream,
    sym: SymbolTable,
    diags: list[Diagnostic],
    qid: Optional[str],
    inner_schemas: Optional[dict],
    allow_windows: bool,
    inferred_targets: Optional[dict] = None,
) -> tuple[bool, Optional[dict]]:
    """Resolve a `from X` source to (found, schema|None-open)."""
    sid = s.stream_id
    line, col = _loc(s)

    def err(code: str, msg: str) -> tuple[bool, Optional[dict]]:
        diags.append(Diagnostic(code, msg, line, col, query=qid))
        return False, None

    if s.is_inner:
        if inner_schemas is not None:
            if sid in inner_schemas:
                return True, inner_schemas[sid]
            return err(
                "SA101",
                f"inner stream '#{sid}' is not produced by an earlier query "
                "in this partition",
            )
        # outside partitions the runtime resolves '#x' as the plain stream x
        if sid in sym.streams:
            return True, sym.streams[sid]
        return err("SA101", f"stream '#{sid}' is not defined")

    if s.is_fault or sid.startswith("!"):
        parent = sid[1:]
        if sid in sym.streams:
            return True, sym.streams[sid]
        if parent in sym.streams:
            return err(
                "SA106",
                f"fault stream '{sid}' does not exist: stream '{parent}' "
                "does not declare @OnError(action='STREAM')",
            )
        return err("SA101", f"stream '{parent}' is not defined")

    if sid in sym.streams:
        return True, sym.streams[sid]
    if allow_windows and sid in sym.windows:
        return True, sym.windows[sid]
    found, schema = _inferred_schema(inferred_targets, sid)
    if found:
        return True, schema
    kind = sym.describe(sid)
    if kind is not None:
        hint = (
            f" ('{sid}' is a {kind} — it cannot be consumed as a stream here)"
        )
    elif not allow_windows and sid in sym.windows:
        hint = f" ('{sid}' is a named window — patterns consume streams only)"
    else:
        hint = ""
    return err("SA101", f"stream '{sid}' is not defined{hint}")


def _apply_handlers(
    s: SingleInputStream,
    schema: Optional[dict],
    ref: str,
    checker: ExprChecker,
    scope: AnalysisScope,
    diags: list[Diagnostic],
    qid: Optional[str],
    allow_windows: bool = True,
) -> Optional[dict]:
    """Walk a source's handler chain (filters / windows / stream functions),
    returning the post-chain schema (None = open). Keeps `scope.refs[ref]`
    up to date so later filters see appended stream-function attrs."""
    cur = dict(schema) if schema is not None else None
    scope.refs[ref] = cur
    saw_window = False
    for h in s.handlers:
        if isinstance(h, Filter):
            t = checker.infer_no_agg(h.expression, scope)
            if t is not None and t is not AttrType.BOOL:
                line, col = _loc(h.expression)
                diags.append(Diagnostic(
                    "SA203",
                    f"filter must be a boolean expression, got {t!r}",
                    line, col, query=qid,
                ))
        elif isinstance(h, WindowHandler):
            if saw_window:
                line, col = _loc(h)
                diags.append(Diagnostic(
                    "SA302", "only one window per stream", line, col, query=qid
                ))
            saw_window = True
            check_window(h.window, checker, scope, diags, qid)
        elif isinstance(h, StreamFunctionHandler):
            ok, new_attrs = check_stream_function(h, checker, scope, diags, qid)
            if not ok:
                continue
            if new_attrs is None:
                cur = None  # extension output: schema now unknown
            elif cur is not None:
                for name, t in new_attrs.items():
                    if name in cur:
                        line, col = _loc(h)
                        diags.append(Diagnostic(
                            "SA302",
                            f"stream function '#{h.name}' output '{name}' "
                            "collides with an existing attribute",
                            line, col, query=qid,
                        ))
                    cur[name] = t
            scope.refs[ref] = cur
    return cur


def _analyze_query(
    query: Query,
    qid: str,
    sym: SymbolTable,
    diags: list[Diagnostic],
    inferred_targets: dict,
    flows: list[QueryFlow],
    inner_schemas: Optional[dict] = None,
    inner_ns: str = "",
) -> Optional[list]:
    """Analyze one query; returns its output attrs (for partition inner
    streams) — list[(name, AttrType|None)] or None when unknown."""
    checker = ExprChecker(sym, diags, query=qid)
    scope = AnalysisScope()
    consumes: set[str] = set()
    star_attrs: Optional[list] = None

    stream = query.input_stream
    if isinstance(stream, SingleInputStream):
        found, schema = _resolve_single_source(
            stream, sym, diags, qid, inner_schemas, allow_windows=True,
            inferred_targets=inferred_targets,
        )
        ref = stream.ref
        scope.add(ref, dict(schema) if schema is not None else None)
        if found and ref != stream.stream_id:
            scope.add(
                stream.stream_id, dict(schema) if schema is not None else None
            )
        scope.default_ref = ref
        if found:
            # inner streams get a per-partition namespaced node id so the
            # dataflow graph connects them to their producers (and two
            # partitions' same-named inner streams stay distinct)
            consumes.add(
                f"{inner_ns}#{stream.stream_id}"
                if stream.is_inner and inner_schemas is not None
                else stream.stream_id
            )
        # handlers are validated even when the source is undefined (open
        # schema): a window/function typo is independent of the stream typo
        out_schema = _apply_handlers(
            stream, schema if found else None, ref, checker, scope, diags, qid
        )
        star_attrs = (
            list(out_schema.items())
            if found and out_schema is not None
            else None
        )

    elif isinstance(stream, JoinInputStream):
        star_attrs = _analyze_join_input(
            stream, checker, scope, sym, diags, qid, consumes, inferred_targets
        )

    elif isinstance(stream, StateInputStream):
        star_attrs = _analyze_state_input(
            stream, checker, scope, sym, diags, qid, consumes, inferred_targets
        )

    out_attrs = _analyze_selector(query.selector, checker, scope, star_attrs)
    produces = _analyze_output(
        query, qid, out_attrs, sym, diags, inferred_targets, scope, checker,
        inner_ns=inner_ns,
    )
    flows.append(QueryFlow(qid, consumes=consumes, produces=produces))
    return out_attrs


def _analyze_join_input(
    join: JoinInputStream,
    checker: ExprChecker,
    scope: AnalysisScope,
    sym: SymbolTable,
    diags: list[Diagnostic],
    qid: str,
    consumes: set,
    inferred_targets: Optional[dict] = None,
) -> Optional[list]:
    side_base: list[Optional[list]] = []
    for s in (join.left, join.right):
        sid = s.stream_id
        line, col = _loc(s)
        schema: Optional[dict]
        if sid in sym.streams:
            schema = sym.streams[sid]
            consumes.add(sid)
        elif sid in sym.tables:
            schema = sym.tables[sid]
        elif sid in sym.windows:
            schema = sym.windows[sid]
            consumes.add(sid)
        elif sid in sym.aggregations:
            schema = None  # aggregation bucket view: open
        elif (inf := _inferred_schema(inferred_targets, sid))[0]:
            schema = inf[1]
            consumes.add(sid)
        elif sid.startswith("!") and sid[1:] in sym.streams:
            diags.append(Diagnostic(
                "SA106",
                f"fault stream '{sid}' does not exist: stream '{sid[1:]}' "
                "does not declare @OnError(action='STREAM')",
                line, col, query=qid,
            ))
            schema = None
        else:
            diags.append(Diagnostic(
                "SA101", f"stream '{sid}' is not defined", line, col, query=qid
            ))
            schema = None
        side_base.append(
            list(schema.items()) if schema is not None else None
        )
        ref = s.ref
        # join scope registers the two side refs only (join.py:404-409)
        post = _apply_handlers(s, schema, ref, checker, scope, diags, qid)
        scope.refs[ref] = post
    scope.default_ref = join.left.ref

    if join.on is not None:
        t = checker.infer_no_agg(join.on, scope)
        if t is not None and t is not AttrType.BOOL:
            line, col = _loc(join.on)
            diags.append(Diagnostic(
                "SA203",
                f"join 'on' must be a boolean expression, got {t!r}",
                line, col, query=qid,
            ))

    _check_join_agg_clauses(join, sym, diags, qid)

    if side_base[0] is None or side_base[1] is None:
        return None
    return side_base[0] + side_base[1]


def _check_join_agg_clauses(
    join: JoinInputStream,
    sym: SymbolTable,
    diags: list[Diagnostic],
    qid: str,
) -> None:
    """`within`/`per` on aggregation joins (runtime analog:
    app_runtime._add_join_query AggFindable construction — every error
    here raises at creation time there). On a join with NO aggregation
    side the clauses are silently ignored by the runtime: warning."""
    from siddhi_tpu.query_api.expression import AttributeFunction

    agg_sides = [
        s for s in (join.left, join.right)
        if s.stream_id in sym.aggregations
    ]
    line, col = _loc(join)
    if line is None:  # the parser stamps the sides, not the join node
        line, col = _loc(join.left)

    def err(msg, node=None, severity=ERROR):
        l2, c2 = _loc(node) if node is not None else (line, col)
        diags.append(Diagnostic(
            "SA117", msg, l2 if l2 is not None else line,
            c2 if c2 is not None else col, severity=severity, query=qid,
        ))

    if not agg_sides:
        if join.within is not None or join.per is not None:
            err(
                "'within'/'per' apply to aggregation joins only — no join "
                "side is an aggregation, the clause is ignored",
                join.within or join.per, severity=WARNING,
            )
        return

    from siddhi_tpu.core.aggregation import parse_per, parse_within_value
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    if join.per is None or not isinstance(join.per, Constant):
        err(
            "joining an aggregation needs per '<duration>' "
            "(a constant like per 'hours')",
            join.per,
        )
        per_dur = None
    else:
        try:
            per_dur = parse_per(join.per.value)
        except SiddhiAppCreationError as exc:
            err(str(exc), join.per)
            per_dur = None

    if per_dur is not None:
        for s in agg_sides:
            ad = sym.aggregation_defs.get(s.stream_id)
            if ad is None or ad.time_period is None:
                continue
            if per_dur not in ad.time_period.durations:
                have = ", ".join(
                    d.name.lower() for d in ad.time_period.durations
                )
                err(
                    f"aggregation '{s.stream_id}' has no "
                    f"'{per_dur.name.lower()}' duration (declares: {have})",
                    join.per,
                )

    w = join.within
    if w is None:
        return
    if isinstance(w, AttributeFunction) and w.name == "__within_range__":
        operands = list(w.parameters)
    else:
        operands = [w]
    for op in operands:
        if not isinstance(op, Constant):
            err("'within' operands must be constants", op)
            return
    try:
        for op in operands:
            parse_within_value(op.value)
    except SiddhiAppCreationError as exc:
        err(str(exc), w)


def _analyze_state_input(
    state_stream: StateInputStream,
    checker: ExprChecker,
    scope: AnalysisScope,
    sym: SymbolTable,
    diags: list[Diagnostic],
    qid: str,
    consumes: set,
    inferred_targets: Optional[dict] = None,
) -> Optional[list]:
    atoms = list(iter_state_streams(state_stream.state))
    # register every ref before checking any atom filter: pattern conditions
    # may reference other state labels (pattern.py scope construction)
    atom_schemas: list[Optional[dict]] = []
    for s in atoms:
        found, schema = _resolve_single_source(
            s, sym, diags, qid, None, allow_windows=False,
            inferred_targets=inferred_targets,
        )
        if found:
            consumes.add(s.stream_id)
        atom_schemas.append(dict(schema) if schema is not None else None)
        scope.add(s.ref, atom_schemas[-1])
    if atoms:
        scope.default_ref = atoms[0].ref

    for s, schema in zip(atoms, atom_schemas):
        atom_scope = scope.child()
        atom_scope.default_ref = s.ref
        atom_scope.prefer_default = True
        _apply_handlers(
            s, schema, s.ref, checker, atom_scope, diags, qid,
            allow_windows=False,
        )
        # appended stream-function attrs become visible pattern-wide
        scope.refs[s.ref] = atom_scope.refs.get(s.ref, schema)

    # select * over a pattern exposes every ref's attrs; duplicates require
    # explicit projection (pattern_runtime.py:70-85)
    flat: list = []
    seen: set = set()
    for s in atoms:
        schema = scope.refs.get(s.ref)
        if schema is None:
            return None
        for name, t in schema.items():
            if name in seen:
                continue
            seen.add(name)
            flat.append((name, t))
    return flat


# ---------------------------------------------------------------------------
# selector
# ---------------------------------------------------------------------------


def _analyze_selector(
    selector: Selector,
    checker: ExprChecker,
    scope: AnalysisScope,
    star_attrs: Optional[list],
) -> Optional[list]:
    """Returns the selector's output attrs [(name, type|None)] or None when
    unknowable (select * over an open input)."""
    qid = checker.query
    out_attrs: Optional[list]

    if selector.select_all:
        out_attrs = list(star_attrs) if star_attrs is not None else None
    else:
        out_attrs = []
        names: set = set()
        prev_allow = checker.allow_aggregators
        checker.allow_aggregators = True
        try:
            for oa in selector.selection_list:
                t = checker.infer(oa.expression, scope)
                name = None
                if oa.rename:
                    name = oa.rename
                elif isinstance(oa.expression, Variable) and oa.expression.attribute:
                    name = oa.expression.attribute
                else:
                    line, col = _loc(oa)
                    checker.diags.append(Diagnostic(
                        "SA210",
                        "expression projections need a name: add `as <name>`",
                        line, col, query=qid,
                    ))
                if name is not None:
                    if name in names:
                        line, col = _loc(oa)
                        checker.diags.append(Diagnostic(
                            "SA211",
                            f"duplicate output attribute '{name}'",
                            line, col, query=qid,
                        ))
                    names.add(name)
                out_attrs.append((name, t))
        finally:
            checker.allow_aggregators = prev_allow

    for v in selector.group_by:
        checker.infer_no_agg(v, scope)

    if selector.having is not None:
        hav_scope = scope.child()
        if out_attrs is not None:
            # output attrs shadow input attrs for unqualified names
            # (selector.py having scope: __out__ level first)
            hav_scope.add("__out__", {n: t for n, t in out_attrs if n})
            hav_scope.default_ref = scope.default_ref
        prev_allow = checker.allow_aggregators
        checker.allow_aggregators = True
        try:
            t = checker.infer(selector.having, hav_scope)
        finally:
            checker.allow_aggregators = prev_allow
        if t is not None and t is not AttrType.BOOL:
            line, col = _loc(selector.having)
            checker.diags.append(Diagnostic(
                "SA203",
                f"having must be a boolean expression, got {t!r}",
                line, col, query=qid,
            ))

    for ob in selector.order_by:
        _check_order_by(ob, checker, scope, out_attrs)

    return out_attrs


def _check_order_by(
    ob: OrderByAttribute,
    checker: ExprChecker,
    scope: AnalysisScope,
    out_attrs: Optional[list],
) -> None:
    var = ob.variable
    t: Optional[AttrType]
    out_names = dict(n_t for n_t in (out_attrs or []) if n_t[0])
    if var.stream_id is None and var.attribute in out_names:
        t = out_names[var.attribute]
    else:
        t = checker.resolve_variable(var, scope)
    if t in (AttrType.STRING, AttrType.OBJECT):
        line, col = _loc(var)
        checker.diags.append(Diagnostic(
            "SA212",
            f"order by '{var.attribute}': STRING/OBJECT sort keys are not "
            "supported (interned ids are not lexicographic)",
            line, col, query=checker.query,
        ))


# ---------------------------------------------------------------------------
# outputs
# ---------------------------------------------------------------------------


def _widening_ok(src: AttrType, dst: AttrType) -> bool:
    return (
        src in NUMERIC_TYPES and dst in NUMERIC_TYPES and promote(src, dst) is dst
    )


def _analyze_output(
    query: Query,
    qid: str,
    out_attrs: Optional[list],
    sym: SymbolTable,
    diags: list[Diagnostic],
    inferred_targets: dict,
    scope: AnalysisScope,
    checker: ExprChecker,
    inner_ns: str = "",
) -> Optional[str]:
    """Validate the query's output clause; returns the produced stream id
    (for dataflow), or None."""
    out = query.output_stream
    line, col = _loc(out)

    if isinstance(out, InsertIntoStream):
        target = out.target
        if out.is_inner:
            return f"{inner_ns}#{target}"  # partition-inner production
        if out.is_fault or target.startswith("!"):
            parent = target[1:]
            if parent in sym.streams and parent not in sym.fault_parents:
                diags.append(Diagnostic(
                    "SA107",
                    f"insert into '{target}': fault streams exist only for "
                    f"streams declaring @OnError(action='STREAM') — add it "
                    f"to '{parent}'",
                    line, col, query=qid,
                ))
                return target
            if parent not in sym.streams:
                diags.append(Diagnostic(
                    "SA101",
                    f"insert into '{target}': stream '{parent}' is not defined",
                    line, col, query=qid,
                ))
                return target

        declared: Optional[dict] = None
        widening = False
        what = "stream"
        if target in sym.tables:
            declared = sym.tables[target]
            widening = True  # tables allow numeric widening on insert
            what = "table"
        elif target in sym.streams:
            declared = sym.streams[target]
        elif target in sym.windows:
            declared = sym.windows[target]
            what = "window"

        if out_attrs is None:
            return target
        if declared is not None:
            _check_insert_schema(
                target, what, out_attrs, list(declared.items()),
                diags, qid, line, col, widening,
            )
        else:
            prior = inferred_targets.get(target)
            if prior is None:
                inferred_targets[target] = list(out_attrs)
            else:
                _check_insert_schema(
                    target, "stream (inferred from an earlier insert)",
                    out_attrs, prior, diags, qid, line, col, False,
                )
        return target

    if isinstance(out, (DeleteStream, UpdateStream, UpdateOrInsertStream)):
        target = out.target
        table = sym.tables.get(target)
        if table is None:
            diags.append(Diagnostic(
                "SA108",
                f"'{target}' is not a defined table "
                f"(tables: {', '.join(sorted(sym.tables)) or 'none'})",
                line, col, query=qid,
            ))
            return None
        if isinstance(out, UpdateOrInsertStream) and out_attrs is not None:
            _check_insert_schema(
                target, "table", out_attrs, list(table.items()),
                diags, qid, line, col, widening=True,
            )
        # on / set clauses resolve against {__out__: selector output, table}
        # with unqualified names preferring the output (table.py:801-805)
        op_scope = AnalysisScope()
        op_scope.add(
            "__out__",
            {n: t for n, t in out_attrs if n} if out_attrs is not None else None,
        )
        op_scope.add(target, table)
        op_scope.default_ref = "__out__"
        op_scope.prefer_default = True
        if out.on is not None:
            t = checker.infer_no_agg(out.on, op_scope)
            if t is not None and t is not AttrType.BOOL:
                oline, ocol = _loc(out.on)
                diags.append(Diagnostic(
                    "SA203",
                    f"'on' must be a boolean expression, got {t!r}",
                    oline, ocol, query=qid,
                ))
        for sa in getattr(out, "set_attributes", None) or []:
            tv = sa.table_variable
            if tv.stream_id is not None and tv.stream_id != target:
                tline, tcol = _loc(tv)
                diags.append(Diagnostic(
                    "SA103",
                    f"set target '{tv.stream_id}.{tv.attribute}' must be a "
                    f"column of table '{target}'",
                    tline, tcol, query=qid,
                ))
            elif table is not None and tv.attribute not in table:
                tline, tcol = _loc(tv)
                diags.append(Diagnostic(
                    "SA103",
                    f"table '{target}' has no column '{tv.attribute}' "
                    f"(has: {', '.join(table)})",
                    tline, tcol, query=qid,
                ))
            checker.infer_no_agg(sa.expression, op_scope)
        return None

    if isinstance(out, ReturnStream):
        return None
    return None


def _check_insert_schema(
    target: str,
    what: str,
    out_attrs: list,
    declared: list,
    diags: list[Diagnostic],
    qid: str,
    line: Optional[int],
    col: Optional[int],
    widening: bool,
) -> None:
    if len(out_attrs) != len(declared):
        diags.append(Diagnostic(
            "SA205",
            f"insert into {what} '{target}': selector emits "
            f"{len(out_attrs)} attribute(s) but the target has "
            f"{len(declared)}",
            line, col, query=qid,
        ))
        return
    for (on_, ot), (tn, tt) in zip(out_attrs, declared):
        if ot is None or tt is None:
            continue
        if ot == tt:
            continue
        if widening and _widening_ok(ot, tt):
            continue
        diags.append(Diagnostic(
            "SA206",
            f"insert into {what} '{target}': output attribute "
            f"'{on_ or '?'}' is {ot!r} but target attribute '{tn}' is {tt!r}",
            line, col, query=qid,
        ))
        return  # first mismatch is enough; the fix usually cascades


# ---------------------------------------------------------------------------
# partitions
# ---------------------------------------------------------------------------


def _analyze_partition(
    part: Partition,
    pid: str,
    sym: SymbolTable,
    diags: list[Diagnostic],
    inferred_targets: dict,
    flows: list[QueryFlow],
    query_ids: list,
) -> None:
    from siddhi_tpu.query_api.execution import (
        RangePartitionType,
        ValuePartitionType,
    )

    checker = ExprChecker(sym, diags, query=pid)
    keyed: set = set()  # streams this partition declares a key for
    for pt in part.partition_types:
        line, col = _loc(pt)
        schema = sym.streams.get(pt.stream_id)
        if pt.stream_id not in sym.streams:
            diags.append(Diagnostic(
                "SA101",
                f"partition: stream '{pt.stream_id}' is not defined",
                line, col, query=pid,
            ))
            continue
        keyed.add(pt.stream_id)
        pscope = AnalysisScope().add(
            pt.stream_id, dict(schema) if schema is not None else None
        )
        if isinstance(pt, ValuePartitionType):
            t = checker.infer_no_agg(pt.expression, pscope)
            if t is AttrType.OBJECT:
                # runtime analog: PartitionRuntime raises 'cannot partition
                # by OBJECT' (partition.py) — OBJECT values have no stable
                # device key encoding
                diags.append(Diagnostic(
                    "SA115",
                    f"partition key over stream '{pt.stream_id}' is "
                    "OBJECT-typed: OBJECT values cannot be partition keys",
                    line, col, query=pid,
                ))
        elif isinstance(pt, RangePartitionType):
            for rng in pt.ranges:
                t = checker.infer_no_agg(rng.condition, pscope)
                if t is not None and t is not AttrType.BOOL:
                    rline, rcol = _loc(rng.condition)
                    diags.append(Diagnostic(
                        "SA203",
                        "range partition condition must be boolean, "
                        f"got {t!r}",
                        rline, rcol, query=pid,
                    ))

    inner_schemas: dict[str, Optional[dict]] = {}
    for qid, q in query_ids:
        _check_partition_keys(q, qid, keyed, sym, diags)
        out_attrs = _analyze_query(
            q, qid, sym, diags, inferred_targets, flows,
            inner_schemas=inner_schemas, inner_ns=pid,
        )
        out = q.output_stream
        if isinstance(out, InsertIntoStream) and out.is_inner:
            inner_schemas[out.target] = (
                {n: t for n, t in out_attrs if n}
                if out_attrs is not None
                else None
            )


# ---------------------------------------------------------------------------
# store queries
# ---------------------------------------------------------------------------


def analyze_store_query(store_query, app) -> AnalysisResult:
    """Semantic analysis of a one-shot store query (`runtime.query(...)`)
    against an app's definitions — the static analog of
    core/store_query.py StoreQueryRuntime creation checks. Accepts the
    StoreQuery AST or SiddhiQL text for either argument; never raises."""
    from siddhi_tpu.query_api.execution import StoreQuery

    diags: list[Diagnostic] = []
    if isinstance(app, str):
        from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler
        from siddhi_tpu.core.errors import SiddhiParserError

        try:
            app = SiddhiCompiler.parse(app)
        except SiddhiParserError as exc:
            return AnalysisResult([Diagnostic(
                "SA001", f"app source: {exc}",
                getattr(exc, "line", None), getattr(exc, "col", None),
            )])
    if isinstance(store_query, str):
        from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler
        from siddhi_tpu.core.errors import SiddhiParserError

        try:
            store_query = SiddhiCompiler.parse_store_query(store_query)
        except SiddhiParserError as exc:
            return AnalysisResult([Diagnostic(
                "SA001", str(exc),
                getattr(exc, "line", None), getattr(exc, "col", None),
            )], app_name=app.name)
    assert isinstance(store_query, StoreQuery)
    try:
        _analyze_store_query(store_query, app, diags)
    except Exception as exc:  # pragma: no cover - analyzer defect guard
        diags.append(Diagnostic(
            "SA000",
            f"internal analyzer error, analysis incomplete: {exc!r}",
            severity=WARNING,
        ))
    return AnalysisResult(diags, app_name=app.name)


def _analyze_store_query(sq, app: SiddhiApp, diags: list[Diagnostic]) -> None:
    from siddhi_tpu.core.aggregation import parse_per, parse_within_value
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    sym = build_symbols(app, [])  # definition defects are the app's report
    qid = "store query"
    checker = ExprChecker(sym, diags, query=qid)
    store = sq.input_store
    line, col = _loc(sq)

    if store is None and sq.output_stream is None:
        diags.append(Diagnostic(
            "SA118",
            "a store query needs a 'from <store>' clause or an "
            "insert/update/delete output",
            line, col, query=qid,
        ))
        return

    schema: Optional[dict] = None
    is_agg = False
    if store is not None:
        sid = store.store_id
        sline, scol = _loc(store)
        if sid in sym.tables:
            schema = sym.tables[sid]
        elif sid in sym.windows:
            schema = sym.windows[sid]
        elif sid in sym.aggregations:
            is_agg = True  # bucket view: schema stays open
        else:
            diags.append(Diagnostic(
                "SA108",
                f"'{sid}' is not a defined table, window, or aggregation "
                f"(tables: {', '.join(sorted(sym.tables)) or 'none'})",
                sline, scol, query=qid,
            ))

        def clause_err(msg, node=None):
            l2, c2 = _loc(node) if node is not None else (sline, scol)
            diags.append(Diagnostic(
                "SA117", msg, l2 if l2 is not None else sline,
                c2 if c2 is not None else scol, query=qid,
            ))

        if is_agg:
            # per '<duration>' is mandatory and must be a declared duration
            if store.per is None or not isinstance(store.per, Constant):
                clause_err(
                    "aggregation store queries need a per '<duration>' "
                    "clause", store.per,
                )
                per_dur = None
            else:
                try:
                    per_dur = parse_per(store.per.value)
                except SiddhiAppCreationError as exc:
                    clause_err(str(exc), store.per)
                    per_dur = None
            ad = sym.aggregation_defs.get(sid)
            if (
                per_dur is not None and ad is not None
                and ad.time_period is not None
                and per_dur not in ad.time_period.durations
            ):
                have = ", ".join(
                    d.name.lower() for d in ad.time_period.durations
                )
                clause_err(
                    f"aggregation '{sid}' has no '{per_dur.name.lower()}' "
                    f"duration (declares: {have})", store.per,
                )
            if store.within is not None:
                w1, w2 = store.within
                operands = [w1] if w2 is None else [w1, w2]
                if not all(isinstance(w, Constant) for w in operands):
                    clause_err("'within' operands must be constants", w1)
                else:
                    try:
                        if w2 is None:
                            lo, hi = parse_within_value(w1.value)
                        else:
                            lo = parse_within_value(w1.value)[0]
                            hi = parse_within_value(w2.value)[0]
                        if lo >= hi:
                            clause_err(
                                "'within' start time must be before the "
                                "end time", w1,
                            )
                    except SiddhiAppCreationError as exc:
                        clause_err(str(exc), w1)
        elif store.within is not None or store.per is not None:
            clause_err(
                "'within'/'per' apply to aggregation store queries",
                store.within[0] if store.within is not None else store.per,
            )

    ref = (store.alias or store.store_id) if store is not None else "__const__"
    # unresolved stores and aggregation bucket views stay OPEN (None): an
    # SA108 is already reported; cascading SA103s would be noise. The
    # no-from insert form exposes a closed empty row (constants only).
    open_schema = store is not None and (is_agg or schema is None)
    scope_schema = (
        dict(schema) if schema is not None
        else (None if open_schema else {})
    )
    scope = AnalysisScope().add(ref, scope_schema)
    if store is not None and ref != store.store_id:
        scope.add(
            store.store_id,
            dict(scope_schema) if scope_schema is not None else None,
        )
    scope.default_ref = ref

    if store is not None and store.on is not None:
        t = checker.infer_no_agg(store.on, scope)
        if t is not None and t is not AttrType.BOOL:
            oline, ocol = _loc(store.on)
            diags.append(Diagnostic(
                "SA203",
                f"'on' must be a boolean expression, got {t!r}",
                oline, ocol, query=qid,
            ))

    star = list(schema.items()) if schema is not None else None
    out_attrs = _analyze_selector(sq.selector, checker, scope, star)

    out = sq.output_stream
    if out is not None:
        target = getattr(out, "target", None)
        oline, ocol = _loc(out)
        if target is None:
            # a ReturnStream output parses but the runtime rejects it: a
            # store-query write must name a table (store_query.py target
            # resolution)
            diags.append(Diagnostic(
                "SA118",
                "a store query write output must target a defined table "
                "(insert into / update / delete <table>)",
                oline if oline is not None else line,
                ocol if ocol is not None else col, query=qid,
            ))
        elif target not in sym.tables:
            diags.append(Diagnostic(
                "SA108",
                f"store query target '{target}' is not a defined table "
                f"(tables: {', '.join(sorted(sym.tables)) or 'none'})",
                oline if oline is not None else line,
                ocol if ocol is not None else col, query=qid,
            ))
        elif isinstance(out, InsertIntoStream) and out_attrs is not None:
            _check_insert_schema(
                target, "table", out_attrs,
                list(sym.tables[target].items()),
                diags, qid, oline, ocol, widening=True,
            )


def _check_partition_keys(
    query: Query,
    qid: str,
    keyed: set,
    sym: SymbolTable,
    diags: list[Diagnostic],
) -> None:
    """SA115: every OUTER stream a partitioned query consumes must have a
    partition key declared (`partition with (expr of Stream, ...)`) — the
    runtime has no way to route its events to a partition slot and raises
    'partition has no key for stream' at creation (partition.py). Inner
    `#streams` arrive already partition-shaped and need no key."""
    stream = query.input_stream
    atoms: list[SingleInputStream] = []
    if isinstance(stream, SingleInputStream):
        atoms = [stream]
    elif isinstance(stream, JoinInputStream):
        atoms = [stream.left, stream.right]
    elif isinstance(stream, StateInputStream):
        atoms = list(iter_state_streams(stream.state))
    for s in atoms:
        sid = s.stream_id
        if s.is_inner or sid in keyed or sid not in sym.streams:
            continue  # inner/keyed are fine; undefined is SA101's job
        line, col = _loc(s)
        diags.append(Diagnostic(
            "SA115",
            f"partition has no key for stream '{sid}': declare one with "
            f"`partition with (<expr> of {sid}, ...)`",
            line, col, query=qid,
        ))
