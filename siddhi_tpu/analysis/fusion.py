"""Fusion-feasibility planner: which queries sharing a stream can compile
into ONE XLA program per chunk.

The fused ingest (core/ingest.py FusedJunctionIngest) already compiles a
junction's entire subscriber fan-out into a single jitted chunk program —
but it only ENGAGES when nothing host-side observes per-batch boundaries
(`eligible()`), and it never reasons about which subset of queries could
fuse when the whole set cannot. This planner decides that statically, from
the AST alone, and emits the contract the whole-graph fusion PR will
implement (ROADMAP "whole-graph query fusion + cross-query state sharing";
TiLT / "To Share or not to Share", PAPERS.md):

* **groups** — per consumed stream, the maximal sets of queries with no
  fusion hazard: every query in a group shares the stream's chunking
  (@app:batch × @app:ingestChunk) and can run inside one `lax.scan` body;
* **blockers** — each query excluded from its stream's group, with the
  specific hazard (mirrors `eligible()` plus static structure):
  `async-ingress` (@async junction has its own worker), `partition`
  (partition boundary: per-key state), `rate-limit` (host-side output
  rate observer), `scheduler` (timer-armed windows/patterns need host
  scheduling between batches), `multi-stream` (joins/patterns spanning
  junctions: cross-junction fusion is out of contract),
  `ordering` (the query's insert target is consumed by another query on
  the same stream: in-group ordering would change delivery);
* **shared-state candidates** — queries over the same stream whose
  filter+window handler chains are structurally identical
  (cost.window_signature): their device window state is byte-identical
  and ONE ring can serve both (reported as SA123 and in the plan with the
  bytes saved).

`build_fusion_plan(app)` returns a versioned `FusionPlan`; `check_fusion`
emits the SA123/SA124 lints from the same computation. Both are pure AST
passes — no runtime, no device.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

from siddhi_tpu.query_api.annotation import find_annotation
from siddhi_tpu.query_api.execution import (
    JoinInputStream,
    Query,
    SingleInputStream,
    StateInputStream,
    WindowHandler,
    iter_state_streams,
)
from siddhi_tpu.query_api.siddhi_app import SiddhiApp

from siddhi_tpu.analysis.cost import (
    AppCostModel,
    _window_cost,
    compute_costs,
    iter_query_entries,
    window_signature,
)
from siddhi_tpu.analysis.diagnostics import WARNING, Diagnostic

# v2: per-stream `wire` section — the versioned WireSpec (core/wire.py)
# naming each consumed stream's analyzer-chosen per-column wire encodings
# plus the predicted logical-vs-encoded bytes/event
# v3: value-analysis facts — `domains` (per-stream inferred abstract
# domains, analysis/values.py), `rewrites` (semantics-preserving rewrite
# opportunities the analysis proved), and wire entries gain inferred-lane
# provenance + prunable dead columns
PLAN_VERSION = 3

# hazard ids, stable (documented in the README; SA124 messages name them)
H_ASYNC = "async-ingress"
H_PARTITION = "partition"
H_RATE = "rate-limit"
H_SCHEDULER = "scheduler"
H_MULTI = "multi-stream"
H_ORDERING = "ordering"
H_KEYSHARD = "keyshard-state"

_HAZARD_WHY = {
    H_ASYNC: "@async ingress runs its own worker; the fused chunk path "
             "never engages on an async junction",
    H_PARTITION: "partition boundary: per-key state cannot join a "
                 "whole-stream program",
    H_RATE: "output rate limiter observes per-batch boundaries on the host",
    H_SCHEDULER: "timer-armed operator needs host scheduling between "
                 "batches",
    H_MULTI: "consumes more than one stream; cross-junction fusion is not "
             "in the plan contract",
    H_ORDERING: "its insert target has downstream consumers: the fused "
                "chunk cannot re-publish per batch without reordering "
                "delivery",
    H_KEYSHARD: "@app:shard axis='keys' key-shards this query's group-by "
                "state across the mesh; its [D] state steps under its own "
                "shard_map program and cannot join a fused chunk body",
}


@dataclasses.dataclass
class FusionPlan:
    """The versioned plan contract consumed by the fusion PR."""

    app_name: str
    batch_size: int
    chunk_batches: int
    groups: list = dataclasses.field(default_factory=list)
    blockers: list = dataclasses.field(default_factory=list)
    shared_state: list = dataclasses.field(default_factory=list)
    # sid -> versioned WireSpec summary (core/wire.py): the static
    # per-column encoding choice for every consumed stream, with the
    # predicted logical-vs-encoded bytes/event
    wire: dict = dataclasses.field(default_factory=dict)
    # v3: semantics-preserving rewrites proven by value analysis
    # (analysis/values.py) — constant folds, always-true conjunct drops,
    # provably-false filters, prunable dead columns
    rewrites: list = dataclasses.field(default_factory=list)
    # v3: sid -> {attr -> abstract-domain dict} from the value fixpoint
    domains: dict = dataclasses.field(default_factory=dict)
    costs: Optional[AppCostModel] = None

    def to_dict(self) -> dict:
        return {
            "version": PLAN_VERSION,
            "app": self.app_name,
            "chunk": {
                "batch_size": self.batch_size,
                "chunk_batches": self.chunk_batches,
            },
            "groups": list(self.groups),
            "blockers": list(self.blockers),
            "shared_state": list(self.shared_state),
            "wire": dict(self.wire),
            "rewrites": list(self.rewrites),
            "domains": dict(self.domains),
            "costs": self.costs.to_dict() if self.costs is not None else None,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=False)

    def summary(self) -> dict:
        """Compact form for EXPLAIN plan annotation."""
        return {
            "version": PLAN_VERSION,
            "groups": [
                {
                    "stream": g["stream"],
                    "queries": g["queries"],
                    "est_dispatch_reduction": g["est_dispatch_reduction"],
                }
                for g in self.groups
            ],
            "blockers": [
                {"query": b["query"], "stream": b["stream"],
                 "hazard": b["hazard"]}
                for b in self.blockers
            ],
            "shared_state": [
                {"stream": s["stream"], "queries": s["queries"],
                 "est_bytes_saved": s["est_bytes_saved"]}
                for s in self.shared_state
            ],
            "rewrites": list(self.rewrites),
        }


@dataclasses.dataclass
class _Consumer:
    qid: str
    query: Query
    in_partition: bool
    streams: list  # every outer STREAM the query consumes (tables/windows/
                   # aggregation sides are passive probes, not consumption)


def _collect_consumers(app: SiddhiApp, defined_streams: set) -> list:
    out: list[_Consumer] = []
    for qid, q, in_part in iter_query_entries(app):
        stream = q.input_stream
        sids: list[str] = []
        if isinstance(stream, SingleInputStream):
            if not stream.is_inner:
                sids = [stream.stream_id]
        elif isinstance(stream, JoinInputStream):
            sids = [
                s.stream_id for s in (stream.left, stream.right)
                if not s.is_inner
            ]
        elif isinstance(stream, StateInputStream):
            sids = [
                s.stream_id
                for s in iter_state_streams(stream.state)
                if not s.is_inner
            ]
        sids = [sid for sid in sids if sid in defined_streams]
        out.append(_Consumer(qid, q, in_part, sids))
    return out


def _keyshard_candidate(q: Query) -> bool:
    """AST-level mirror of `parallel/keyshard.keyed_shardable`: a plain
    windowless grouped single-stream query with no host-side ordering
    state. Deliberately a SUPERSET of the runtime predicate (table probes
    are invisible here) — a vetoed-but-ultimately-unsharded query simply
    rides the residual per-batch path, which is always correct."""
    sel = q.selector
    if not getattr(sel, "group_by", None):
        return False
    stream = q.input_stream
    if not isinstance(stream, SingleInputStream) or stream.is_inner:
        return False
    if any(isinstance(h, WindowHandler) for h in stream.handlers):
        return False
    if q.output_rate is not None:
        return False
    if sel.order_by or sel.limit is not None or sel.offset is not None:
        return False
    return True


def _query_hazard(
    c: _Consumer, model: AppCostModel, observed_targets: set,
    keyshard: bool = False,
) -> Optional[str]:
    """First fusion hazard excluding query `c` from its stream's group,
    or None when it can fuse. Order matters: report the most structural
    hazard first."""
    if c.in_partition:
        return H_PARTITION
    # distinct streams the query consumes (an aliased self-join is one)
    if len(set(c.streams)) > 1:
        return H_MULTI
    if c.query.output_rate is not None:
        return H_RATE
    qc = model.queries.get(c.qid)
    if qc is not None and qc.scheduler_armed:
        return H_SCHEDULER
    if keyshard and _keyshard_candidate(c.query):
        return H_KEYSHARD
    target = getattr(c.query.output_stream, "target", None)
    if target is not None and target in observed_targets:
        return H_ORDERING
    return None


def build_fusion_plan(
    app: SiddhiApp, sym=None, model: Optional[AppCostModel] = None,
    values=None,
) -> FusionPlan:
    """Pure AST pass; never raises on semantically-bad apps (unknown
    streams simply do not form groups)."""
    from siddhi_tpu.analysis.symbols import build_symbols

    if sym is None:
        sym = build_symbols(app, [])
    if values is None:
        try:
            from siddhi_tpu.analysis.values import analyze_values

            values = analyze_values(app, sym)
        except Exception:  # pragma: no cover — plan must survive bad apps
            values = None
    if model is None:
        model = compute_costs(app, sym, values=values)

    plan = FusionPlan(
        app.name, model.batch_size, model.chunk_batches, costs=model
    )
    # @app:shard axis='keys' (or the env overrides) key-shards eligible
    # grouped queries out of fused groups — same resolution the runtime
    # uses, so the plan and ShardRuntime placement can never disagree
    keyshard_on = False
    try:
        from siddhi_tpu.parallel.shard import resolve_shard_annotation

        devs, axis = resolve_shard_annotation(
            find_annotation(app.annotations, "app:shard")
        )
        keyshard_on = devs >= 2 and axis == "keys"
    except Exception:  # pragma: no cover — plan must survive bad apps
        keyshard_on = False
    consumers = _collect_consumers(app, set(sym.streams))

    # streams whose defined consumers number >= 2 are fusion-planning
    # targets; single-consumer streams already fuse trivially via the
    # existing per-junction ingest
    by_stream: dict[str, list] = {}
    for c in consumers:
        for sid in sorted(set(c.streams)):
            if sid in sym.streams:
                by_stream.setdefault(sid, []).append(c)

    # streams whose batch boundaries something host-side observes: any
    # query consumes them, or a @sink delivers from them (mirror of
    # eligible()'s insert-target-junction check, core/ingest.py)
    observed_targets: set = set(sym.sinked)
    for c in consumers:
        observed_targets.update(c.streams)

    for sid in sorted(by_stream):
        cs = by_stream[sid]
        if len(cs) < 2:
            continue
        async_ann = None
        d = app.stream_definitions.get(sid)
        if d is not None:
            async_ann = find_annotation(d.annotations, "async")
        fusable: list[_Consumer] = []
        for c in cs:
            hazard = H_ASYNC if async_ann is not None else _query_hazard(
                c, model, observed_targets, keyshard=keyshard_on
            )
            if hazard is None:
                fusable.append(c)
            else:
                plan.blockers.append({
                    "stream": sid,
                    "query": c.qid,
                    "hazard": hazard,
                    "why": _HAZARD_WHY[hazard],
                })
        if len(fusable) >= 2:
            n = len(fusable)
            K = model.chunk_batches
            state_bytes = sum(
                model.queries[c.qid].state_bytes
                for c in fusable if c.qid in model.queries
            )
            plan.groups.append({
                "stream": sid,
                # telemetry component of the group's chunk program — the
                # fusion executor (core/fusion_exec.py) adopts this name, so
                # the static plan, runtime.explain(), and /profile all key
                # the same ledger
                "component": f"stream.{sid}.fusedgroup.{len(plan.groups)}",
                "queries": sorted(c.qid for c in fusable),
                "chunk": {
                    "batch_size": model.batch_size,
                    "chunk_batches": K,
                },
                "state_bytes": state_bytes,
                # today: n per-batch dispatches per micro-batch; fused: one
                # dispatch per K-batch chunk running all n bodies
                "dispatches_per_chunk_before": n * K,
                "dispatches_per_chunk_after": 1,
                "est_dispatch_reduction": round(1.0 - 1.0 / (n * K), 4),
            })

    _collect_shared_state(app, sym, model, consumers, plan)
    _collect_wire_specs(app, sym, model, plan, values)
    if values is not None:
        plan.rewrites = list(values.rewrites)
        plan.domains = values.domains_dict()
    return plan


def _collect_wire_specs(
    app: SiddhiApp, sym, model: AppCostModel, plan: FusionPlan,
    values=None,
) -> None:
    """Per consumed stream: the static WireSpec (core/wire.py — the same
    builder the runtime's fused ingest consumes, so the plan and the
    engine can never choose different encoders) plus the predicted
    logical-vs-encoded bytes/event. Sampling can only shrink the wire
    further at runtime (narrow tsd, un-hinted int columns)."""
    from siddhi_tpu.core.wire import (
        WIRE_SPEC_VERSION,
        app_wire_specs,
        encoding_label,
        estimate_wire_bytes,
        logical_row_bytes,
    )

    inferred = None
    if values is not None:
        try:
            from siddhi_tpu.analysis.values import infer_wire_hints

            inferred = infer_wire_hints(values, sym)
        except Exception:  # pragma: no cover
            inferred = None
    disabled, specs = app_wire_specs(
        app, sym.streams, sorted(model.streams), model.batch_size,
        inferred=inferred,
    )
    dead = getattr(values, "dead_columns", None) or {}
    for sid, (attrs, spec) in specs.items():
        entry = {
            "version": WIRE_SPEC_VERSION,
            "source": spec.source if spec is not None else "static",
            "encodings": {
                lane: encoding_label(e)
                for lane, e in sorted(
                    (spec.encodings if spec is not None else {}).items()
                )
            },
            "logical_B_per_ev": logical_row_bytes(attrs),
            "encoded_B_per_ev_est": estimate_wire_bytes(
                attrs, spec, capacity=model.batch_size
            ),
        }
        if spec is not None and spec.inferred_lanes:
            entry["inferred_lanes"] = sorted(spec.inferred_lanes)
        if sid in dead:
            entry["pruned"] = list(dead[sid])
        if disabled:
            entry["disabled"] = True
        plan.wire[sid] = entry


def _collect_shared_state(
    app: SiddhiApp, sym, model: AppCostModel, consumers: list,
    plan: FusionPlan,
) -> None:
    """Identical (filter-chain + window) sources over the same stream:
    their device rings hold byte-identical content — one ring can serve
    every query in the set ("To Share or not to Share", PAPERS.md)."""
    sigs: dict[tuple, list] = {}
    for c in consumers:
        stream = c.query.input_stream
        sources = []
        if isinstance(stream, SingleInputStream):
            sources = [stream]
        elif isinstance(stream, JoinInputStream):
            sources = [stream.left, stream.right]
        for s in sources:
            if s.is_inner or s.stream_id not in sym.streams:
                continue
            sig = window_signature(s.handlers)
            if sig is None:
                continue
            sigs.setdefault((s.stream_id, sig), []).append((c.qid, s))
    for (sid, sig), entries in sorted(sigs.items()):
        qids = sorted({qid for qid, _s in entries})
        if len(qids) < 2:
            continue
        # size ONLY the shared source's own window chain — the query may
        # hold other window state (e.g. the opposite join side) that
        # sharing this ring cannot save
        _qid0, s0 = entries[0]
        schema = sym.streams.get(sid)
        per_query = sum(
            _window_cost(h.window, schema, _qid0).state_bytes
            for h in s0.handlers if isinstance(h, WindowHandler)
        )
        plan.shared_state.append({
            "stream": sid,
            "signature": sig,
            "queries": qids,
            "est_bytes_saved": per_query * (len(qids) - 1),
        })


# ---------------------------------------------------------------------------
# lints: SA123 / SA124
# ---------------------------------------------------------------------------


def check_fusion(
    app: SiddhiApp, sym, diags: list, model: Optional[AppCostModel] = None,
    values=None,
) -> FusionPlan:
    plan = build_fusion_plan(app, sym, model, values=values)
    nodes = {qid: q for qid, q, _in_part in iter_query_entries(app)}

    # SA123: identical window duplicated across queries (shareable)
    for entry in plan.shared_state:
        qids = entry["queries"]
        # anchor the diagnostic on the LAST duplicate's window handler
        loc_qid, node = _shared_loc(nodes, entry)
        diags.append(Diagnostic(
            "SA123",
            f"identical window state over stream '{entry['stream']}' in "
            f"queries {', '.join(qids)} ({entry['signature']}): one shared "
            f"ring could serve all of them, saving "
            f"~{entry['est_bytes_saved']} bytes of device state",
            getattr(node, "line", None), getattr(node, "col", None),
            severity=WARNING, query=loc_qid,
        ))

    # SA124: a hazard split a would-be group
    for b in plan.blockers:
        node = nodes.get(b["query"])
        diags.append(Diagnostic(
            "SA124",
            f"query cannot fuse with the other consumers of stream "
            f"'{b['stream']}': {b['hazard']} ({b['why']})",
            getattr(node, "line", None), getattr(node, "col", None),
            severity=WARNING, query=b["query"],
        ))
    return plan


def render_plan_text(plan: FusionPlan) -> str:
    """Human-readable FusionPlan (CLI `--plan` default format)."""
    from siddhi_tpu.analysis.cost import _fmt_bytes

    lines = [
        f"FUSION PLAN v{PLAN_VERSION} — app '{plan.app_name}'  "
        f"(batch={plan.batch_size} x chunk={plan.chunk_batches})"
    ]
    if plan.groups:
        lines.append("fusable groups:")
        for g in plan.groups:
            lines.append(
                f"  stream {g['stream']}: {', '.join(g['queries'])}  "
                f"({g['dispatches_per_chunk_before']} dispatches/chunk -> "
                f"{g['dispatches_per_chunk_after']}, "
                f"-{g['est_dispatch_reduction'] * 100:.1f}% dispatch, "
                f"state={_fmt_bytes(g['state_bytes'])})"
            )
    else:
        lines.append("fusable groups: none (no stream has 2+ fusable consumers)")
    if plan.shared_state:
        lines.append("shared-state candidates:")
        for s in plan.shared_state:
            lines.append(
                f"  stream {s['stream']}: {', '.join(s['queries'])} share "
                f"{s['signature']}  "
                f"(~{_fmt_bytes(s['est_bytes_saved'])} saved)"
            )
    if plan.blockers:
        lines.append("blockers:")
        for b in plan.blockers:
            lines.append(
                f"  {b['query']} on {b['stream']}: {b['hazard']} — {b['why']}"
            )
    encoded_streams = {
        sid: w for sid, w in plan.wire.items() if w.get("encodings")
    }
    if encoded_streams:
        lines.append("wire encodings:")
        for sid, w in sorted(encoded_streams.items()):
            encs = ", ".join(
                f"{lane}={label}"
                + ("*" if lane in w.get("inferred_lanes", []) else "")
                for lane, label in w["encodings"].items()
            )
            suffix = ""
            if w.get("inferred_lanes"):
                suffix += ", *=inferred"
            if w.get("pruned"):
                suffix += f", pruned: {', '.join(w['pruned'])}"
            lines.append(
                f"  stream {sid}: {encs}  "
                f"({w['logical_B_per_ev']} -> ~{w['encoded_B_per_ev_est']} "
                f"B/ev{', DISABLED' if w.get('disabled') else ''}{suffix})"
            )
    if plan.rewrites:
        lines.append("rewrites (value analysis):")
        for r in plan.rewrites:
            detail = ", ".join(
                f"{k}={v}" for k, v in sorted(r.items()) if k != "kind"
            )
            lines.append(f"  {r['kind']}: {detail}")
    if plan.costs is not None:
        lines.append("per-query cost:")
        for qid, qc in sorted(plan.costs.queries.items()):
            progs = ", ".join(
                f"{p.component}~{p.predicted_compiles}c"
                for p in qc.programs
            )
            lines.append(
                f"  {qid} [{qc.kind}]: state={_fmt_bytes(qc.state_bytes)} "
                f"sel~{qc.est_selectivity} compiles~{qc.predicted_compiles}"
                + (f"  ({progs})" if progs else "")
            )
    return "\n".join(lines)


def _shared_loc(nodes: dict, entry: dict):
    """(qid, AST node) of the last duplicated window handler, for SA123's
    source location."""
    last = (entry["queries"][-1], None)
    for qid in entry["queries"]:
        q = nodes.get(qid)
        if q is None:
            continue
        stream = q.input_stream
        sources = []
        if isinstance(stream, SingleInputStream):
            sources = [stream]
        elif isinstance(stream, JoinInputStream):
            sources = [stream.left, stream.right]
        for s in sources:
            if s.stream_id != entry["stream"]:
                continue
            if window_signature(s.handlers) != entry["signature"]:
                continue
            for h in s.handlers:
                if isinstance(h, WindowHandler):
                    last = (qid, h.window)
    return last
