"""Static per-query cost model over the analyzer's dataflow graph.

For every query the model predicts, without constructing any runtime stage:

* **device state tensors** — the shapes/dtypes each operator keeps resident
  on device (window rings, batch buckets, pattern token tables + capture
  lanes, join side buffers, group-by key tables, aggregation bucket
  tables), with a byte estimate per operator and per query;
* **jitted programs + predicted compile counts** — one per-batch step
  program per query (two per join side, one per pattern input stream),
  named with the SAME component ids the compile telemetry uses
  (`query.{qid}`, `query.{qid}[sid]`, `stream.{sid}.fused`), and the
  statically-predictable entries of the profiler's recompile-cause
  taxonomy (observability/profiler.py): `first_compile` always,
  `shape_change` for scheduler-armed programs (timer batches carry their
  own shape) and for consumers of query-produced streams (re-published
  slices), `tail_variant_k` for the fused chunk program's power-of-two
  tail ladder (core/ingest.py `_chunk_K`), `full_width_rebuild` when the
  stream wire carries interned STRING/OBJECT columns the narrow-width
  sampling can misfit on;
* **selectivity estimates** — coarse static per-operator output/input
  ratios (documented in `_SEL`), multiplied into a per-query estimate the
  fusion planner and EXPLAIN surface next to the live measured value.

The model mirrors the runtime's sizing rules (`core/windows.py
make_window`, `core/pattern.py PatternProgram`, `core/join.py`,
`core/app_runtime.py` capacity annotations) but never imports a runtime
stage; unknowable quantities (extension windows, non-constant parameters)
degrade to `None`/0 rather than guesses.

Lints emitted by `check_costs` (all warnings — these apps run; they are
hazards, not defects):

* SA120 — `every` pattern with no `within` bound anywhere on the element:
  partial-match tokens are never killed, so the fixed token table
  (@app:patternCapacity) fills and matches drop;
* SA121 — window/aggregation state above the device budget
  (SIDDHI_TPU_STATE_BUDGET_MB, default 64 MiB), or a named window defined
  with no window type at all (unbounded retention);
* SA122 — statically-predicted recompile churn: a fused chunk size whose
  tail-variant ladder alone compiles >= _TAIL_CHURN variants of the whole
  chunk program, or an @app:batch size != 64 on a query consuming a
  query-produced stream (re-published slices arrive <= 64 rows, a second
  shape signature per downstream program).
"""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Optional

from siddhi_tpu.core.types import AttrType
from siddhi_tpu.query_api.annotation import find_annotation
from siddhi_tpu.query_api.definition import WindowSpec
from siddhi_tpu.query_api.execution import (
    AbsentStreamStateElement,
    CountStateElement,
    EveryStateElement,
    Filter,
    JoinInputStream,
    LogicalStateElement,
    NextStateElement,
    Query,
    SingleInputStream,
    StateInputStream,
    StreamStateElement,
    WindowHandler,
    assign_execution_ids,
    iter_state_streams,
)
from siddhi_tpu.query_api.expression import Constant
from siddhi_tpu.query_api.siddhi_app import SiddhiApp

from siddhi_tpu.analysis.diagnostics import WARNING, Diagnostic

# Runtime sizing defaults, mirrored (NOT imported — the model must not pull
# in runtime stages): app_runtime.DEFAULT_BATCH, windows.DEFAULT_TIME_CAPACITY,
# join.DEFAULT_JOIN_CAPACITY, pattern.DEFAULT_TOKEN_CAPACITY /
# DEFAULT_COUNT_CAPACITY, groupby.DEFAULT_GROUP_CAPACITY, the fused ingest
# chunk default (app_runtime._wire_fused_ingest), and agg group capacity.
DEFAULT_BATCH = 64
DEFAULT_TIME_CAPACITY = 1024
DEFAULT_JOIN_CAPACITY = 512
DEFAULT_TOKEN_CAPACITY = 128
DEFAULT_COUNT_CAPACITY = 8
DEFAULT_CHUNK_BATCHES = 32
DEFAULT_GROUP_CAPACITY = 1024
DEFAULT_AGG_GROUPS = 64

# physical widths on device (core/types.py PHYSICAL_DTYPE)
_NBYTES = {
    AttrType.STRING: 4,
    AttrType.INT: 4,
    AttrType.LONG: 8,
    AttrType.FLOAT: 4,
    AttrType.DOUBLE: 4,  # runs as f32 on TPU
    AttrType.BOOL: 1,
    AttrType.OBJECT: 4,
}
_DTYPE_NAME = {
    AttrType.STRING: "int32",
    AttrType.INT: "int32",
    AttrType.LONG: "int64",
    AttrType.FLOAT: "float32",
    AttrType.DOUBLE: "float32",
    AttrType.BOOL: "bool",
    AttrType.OBJECT: "int32",
}

# static per-operator selectivity estimates (events out per event in);
# coarse by design — the live ledger replaces them once traffic flows
_SEL = {
    "filter": 0.25,
    "window:sliding": 2.0,   # CURRENT + its later EXPIRED
    "window:batch": 1.0,     # every event leaves in exactly one flush
    "pattern": 0.05,
    "join": 0.1,
    "having": 0.5,
}

# SA121: device state budget per operator
_BUDGET_MB_ENV = "SIDDHI_TPU_STATE_BUDGET_MB"
DEFAULT_STATE_BUDGET_MB = 64

# SA122: tail ladders at least this long are flagged as churn
_TAIL_CHURN = 8

# window classification (batch vs ring, scheduler arming, row bounds)
# lives ON WindowSpec as state-bound metadata (query_api/definition.py)
_BUILTIN_WINDOWS = {
    "length", "time", "timelength", "externaltime", "lengthbatch",
    "timebatch", "externaltimebatch", "sort", "frequent", "lossyfrequent",
    "cron",
}


def state_budget_bytes() -> int:
    try:
        mb = int(os.environ.get(_BUDGET_MB_ENV, DEFAULT_STATE_BUDGET_MB))
    except ValueError:
        mb = DEFAULT_STATE_BUDGET_MB
    return mb << 20


def _fmt_bytes(n: Optional[int]) -> str:
    if n is None:
        return "?"
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f}MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KiB"
    return f"{n}B"


# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TensorSpec:
    """One resident device buffer: `{lane: (shape) dtype}`."""

    lane: str
    shape: tuple
    dtype: str

    @property
    def bytes(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        width = {"int32": 4, "int64": 8, "float32": 4, "bool": 1}[self.dtype]
        return n * width

    def to_dict(self) -> dict:
        return {
            "lane": self.lane,
            "shape": list(self.shape),
            "dtype": self.dtype,
            "bytes": self.bytes,
        }


@dataclasses.dataclass
class OperatorCost:
    """One stateful operator's predicted device footprint."""

    op: str                      # 'window:length', 'pattern', 'join:left', ...
    detail: str                  # 'length(50)', 'pattern 3 slots T=128', ...
    tensors: list = dataclasses.field(default_factory=list)
    est_selectivity: Optional[float] = None
    line: Optional[int] = None
    col: Optional[int] = None

    @property
    def state_bytes(self) -> int:
        return sum(t.bytes for t in self.tensors)

    def to_dict(self) -> dict:
        return {
            "op": self.op,
            "detail": self.detail,
            "state_bytes": self.state_bytes,
            "tensors": [t.to_dict() for t in self.tensors],
            "est_selectivity": self.est_selectivity,
        }


@dataclasses.dataclass
class ProgramCost:
    """One jitted device program: telemetry component name + the compile
    count the profiler is predicted to observe, by cause."""

    component: str
    input_rows: Optional[int] = None
    predicted_causes: dict = dataclasses.field(default_factory=dict)

    @property
    def predicted_compiles(self) -> int:
        return sum(self.predicted_causes.values())

    def to_dict(self) -> dict:
        return {
            "component": self.component,
            "input_rows": self.input_rows,
            "predicted_compiles": self.predicted_compiles,
            "predicted_causes": dict(sorted(self.predicted_causes.items())),
        }


@dataclasses.dataclass
class QueryCost:
    qid: str
    kind: str  # 'single' | 'join' | 'pattern'
    operators: list = dataclasses.field(default_factory=list)
    programs: list = dataclasses.field(default_factory=list)
    scheduler_armed: bool = False
    rate_limited: bool = False
    in_partition: bool = False
    consumed_streams: list = dataclasses.field(default_factory=list)

    @property
    def state_bytes(self) -> int:
        return sum(o.state_bytes for o in self.operators)

    @property
    def predicted_compiles(self) -> int:
        return sum(p.predicted_compiles for p in self.programs)

    @property
    def est_selectivity(self) -> float:
        sel = 1.0
        for o in self.operators:
            if o.est_selectivity is not None:
                sel *= o.est_selectivity
        return round(sel, 4)

    def to_dict(self) -> dict:
        return {
            "qid": self.qid,
            "kind": self.kind,
            "state_bytes": self.state_bytes,
            "est_selectivity": self.est_selectivity,
            "predicted_compiles": self.predicted_compiles,
            "scheduler_armed": self.scheduler_armed,
            "rate_limited": self.rate_limited,
            "in_partition": self.in_partition,
            "consumed_streams": list(self.consumed_streams),
            "operators": [o.to_dict() for o in self.operators],
            "programs": [p.to_dict() for p in self.programs],
        }


@dataclasses.dataclass
class StreamCost:
    """Per-stream fused chunk program prediction (core/ingest.py)."""

    stream_id: str
    wire_row_bytes: Optional[int]
    chunk_batches: int
    tail_variants: list = dataclasses.field(default_factory=list)
    narrow_rebuild_hazard: bool = False

    def predicted_causes(self) -> dict:
        causes = {"first_compile": 1}
        if self.tail_variants:
            causes["tail_variant_k"] = len(self.tail_variants)
        if self.narrow_rebuild_hazard:
            causes["full_width_rebuild"] = 1
        return causes

    def to_dict(self) -> dict:
        return {
            "stream": self.stream_id,
            "component": f"stream.{self.stream_id}.fused",
            "wire_row_bytes": self.wire_row_bytes,
            "chunk_batches": self.chunk_batches,
            "tail_variants": list(self.tail_variants),
            "narrow_rebuild_hazard": self.narrow_rebuild_hazard,
            "predicted_compiles": sum(self.predicted_causes().values()),
            "predicted_causes": self.predicted_causes(),
        }


@dataclasses.dataclass
class AppCostModel:
    app_name: str
    batch_size: int
    chunk_batches: int
    queries: dict = dataclasses.field(default_factory=dict)  # qid -> QueryCost
    streams: dict = dataclasses.field(default_factory=dict)  # sid -> StreamCost

    def to_dict(self) -> dict:
        return {
            "app": self.app_name,
            "batch_size": self.batch_size,
            "chunk_batches": self.chunk_batches,
            "queries": {
                qid: qc.to_dict() for qid, qc in sorted(self.queries.items())
            },
            "streams": {
                sid: sc.to_dict() for sid, sc in sorted(self.streams.items())
            },
        }


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _const_int(expr) -> Optional[int]:
    if isinstance(expr, Constant) and isinstance(expr.value, (int, float)) \
            and not isinstance(expr.value, bool):
        return int(expr.value)
    return None


def _capacity_annotation(app: SiddhiApp, name: str, default: int) -> int:
    ann = find_annotation(app.annotations, name)
    if ann is None:
        return default
    v = ann.element("size") or ann.element(None)
    try:
        return int(v)
    except (TypeError, ValueError):
        return default


def _schema_tensors(
    schema: Optional[dict], rows: int, prefix: str = "cols",
    facts: Optional[dict] = None,
) -> list:
    """Per-attribute (rows,) lanes for a resolved schema; [] when open.
    With `facts` (attr -> ValueFact from analysis/values.py), a LONG lane
    whose proven interval fits int32 is sized at the narrowed width — the
    same downcast the wire/state layer applies once the proof holds."""
    if schema is None:
        return []
    out = []
    for name, t in schema.items():
        if t is None:
            t = AttrType.LONG  # unknown attr type: widest assumption
        dt = _DTYPE_NAME[t]
        if facts is not None and t is AttrType.LONG:
            f = facts.get(name)
            if f is not None and f.lo is not None and f.hi is not None \
                    and -(2 ** 31) <= f.lo and f.hi < 2 ** 31:
                dt = "int32"
        out.append(TensorSpec(f"{prefix}.{name}", (rows,), dt))
    return out


def window_signature(handlers) -> Optional[str]:
    """Canonical signature of a source's filter+window handler chain up to
    and including its window — two sources with the same signature over the
    same stream hold byte-identical device state (the fusion planner's
    shared-state test). None when the source has no window."""
    parts: list[str] = []
    saw_window = False
    for h in handlers:
        if isinstance(h, Filter):
            parts.append(f"filter[{expr_signature(h.expression)}]")
        elif isinstance(h, WindowHandler):
            w = h.window
            args = ",".join(expr_signature(p) for p in w.parameters)
            parts.append(f"window.{w.key}({args})")
            saw_window = True
        else:  # stream functions change the flow: state diverges
            parts.append(f"fn.{getattr(h, 'name', '?')}")
    return " ".join(parts) if saw_window else None


def expr_signature(expr) -> str:
    """Canonical structural signature of an expression AST (ignores source
    positions — they are class attributes, not dataclass fields). Compact
    for the common node kinds so SA123 messages stay readable."""
    from siddhi_tpu.query_api import expression as E

    if isinstance(expr, Constant):
        return f"{expr.value!r}"
    if isinstance(expr, E.Variable):
        pre = f"{expr.stream_id}." if expr.stream_id else ""
        idx = f"[{expr.stream_index}]" if getattr(expr, "stream_index", None) is not None else ""
        return f"{pre}{expr.attribute}{idx}"
    if isinstance(expr, E.Compare):
        return (
            f"({expr_signature(expr.left)} {expr.op.value} "
            f"{expr_signature(expr.right)})"
        )
    _ARITH_OPS = {
        E.Add: "+", E.Subtract: "-", E.Multiply: "*", E.Divide: "/",
        E.Mod: "%",
    }
    for cls, op in _ARITH_OPS.items():
        if type(expr) is cls:
            return (
                f"({expr_signature(expr.left)} {op} "
                f"{expr_signature(expr.right)})"
            )
    if isinstance(expr, E.And):
        return f"({expr_signature(expr.left)} and {expr_signature(expr.right)})"
    if isinstance(expr, E.Or):
        return f"({expr_signature(expr.left)} or {expr_signature(expr.right)})"
    if isinstance(expr, E.Not):
        return f"(not {expr_signature(expr.expression)})"
    if isinstance(expr, E.AttributeFunction):
        ns = f"{expr.namespace}:" if expr.namespace else ""
        args = ", ".join(expr_signature(p) for p in expr.parameters)
        return f"{ns}{expr.name}({args})"
    if dataclasses.is_dataclass(expr):
        fields = []
        for f in dataclasses.fields(expr):
            v = getattr(expr, f.name)
            if isinstance(v, (list, tuple)):
                sig = "[" + ",".join(expr_signature(x) for x in v) + "]"
            else:
                sig = expr_signature(v)
            fields.append(f"{f.name}={sig}")
        return f"{type(expr).__name__}({','.join(fields)})"
    if isinstance(expr, (str, int, float, bool)) or expr is None:
        return repr(expr)
    if isinstance(expr, enum.Enum):
        return str(expr.value)
    return type(expr).__name__


def _window_cost(
    spec: WindowSpec, schema: Optional[dict], qid: Optional[str],
    facts: Optional[dict] = None,
) -> OperatorCost:
    """Mirror core/windows.py make_window sizing for one window handler,
    reading the state-bound metadata WindowSpec itself carries."""
    name = spec.key
    line, col = getattr(spec, "line", None), getattr(spec, "col", None)
    params = spec.parameters

    detail = f"{name}({', '.join(str(_const_int(p)) if _const_int(p) is not None else '?' for p in params)})"
    if name not in _BUILTIN_WINDOWS:
        return OperatorCost(
            "window:extension", detail, [], None, line, col
        )

    is_batch = spec.is_batch
    rows = spec.length_bound()
    if rows is None:
        if name in ("length", "timelength", "lengthbatch", "sort",
                    "frequent", "lossyfrequent"):
            # declared row bound is non-constant/missing: unknowable
            return OperatorCost(f"window:{name}", detail, [], None, line, col)
        rows = DEFAULT_TIME_CAPACITY  # time-capacity ring family

    buffers = 2 if is_batch else 1  # batch windows carry cur + prev buckets
    tensors = []
    for b in range(buffers):
        pref = ("cur" if b == 0 else "prev") if buffers == 2 else "ring"
        tensors.extend(
            _schema_tensors(schema, rows, prefix=f"{pref}", facts=facts)
        )
        tensors.append(TensorSpec(f"{pref}.ts", (rows,), "int64"))
        if not is_batch:
            # sliding family: wts + seq ordering lanes (windows.py init_state)
            tensors.append(TensorSpec(f"{pref}.wts", (rows,), "int64"))
            tensors.append(TensorSpec(f"{pref}.seq", (rows,), "int64"))
    sel = _SEL["window:batch"] if is_batch else _SEL["window:sliding"]
    return OperatorCost(f"window:{name}", detail, tensors, sel, line, col)


def _source_operators(
    s: SingleInputStream,
    schema: Optional[dict],
    qid: str,
    facts: Optional[dict] = None,
) -> tuple[list, bool]:
    """(operators, scheduler_armed) for one single-source handler chain.
    With `facts` (attr -> ValueFact), a filter whose predicate narrows a
    PROVEN bounded domain gets an interval-overlap selectivity estimate
    in place of the flat default, and window rings size at narrowed
    widths."""
    ops: list[OperatorCost] = []
    armed = False
    for h in s.handlers:
        if isinstance(h, Filter):
            sel = _SEL["filter"]
            if facts:
                try:
                    from siddhi_tpu.analysis.values import (
                        filter_selectivity,
                    )

                    refined = filter_selectivity(h.expression, facts)
                    if refined is not None:
                        sel = refined
                except Exception:  # pragma: no cover - defect guard
                    pass
            ops.append(OperatorCost(
                "filter", "filter", [], sel,
                getattr(h, "line", None), getattr(h, "col", None),
            ))
        elif isinstance(h, WindowHandler):
            ops.append(_window_cost(h.window, schema, qid, facts))
            armed = armed or h.window.arms_scheduler
    return ops, armed


def _pattern_cost(
    stream: StateInputStream,
    sym,
    app: SiddhiApp,
    qid: str,
) -> OperatorCost:
    """Token table + capture lanes, mirroring core/pattern.py init_state.
    Capture lanes are an upper bound (the runtime prunes to selector-used
    attributes; statically we charge the full schema)."""
    T = _capacity_annotation(app, "app:patternCapacity", DEFAULT_TOKEN_CAPACITY)
    count_cap = _capacity_annotation(
        app, "app:countCapacity", DEFAULT_COUNT_CAPACITY
    )
    tensors = [
        TensorSpec("tok.active", (T,), "bool"),
        TensorSpec("tok.slot", (T,), "int32"),
        TensorSpec("tok.start_ts", (T,), "int64"),
        TensorSpec("tok.entry_ts", (T,), "int64"),
    ]
    n_slots = 0

    def walk(elem) -> None:
        nonlocal n_slots
        if isinstance(elem, CountStateElement):
            mx = elem.max_count
            c = mx if 0 < mx <= count_cap else count_cap
            n_slots += 1
            add_ref(elem.stream.stream, c)
        elif isinstance(elem, NextStateElement):
            walk(elem.state)
            walk(elem.next)
        elif isinstance(elem, EveryStateElement):
            walk(elem.state)
        elif isinstance(elem, LogicalStateElement):
            n_slots += 1
            for side in (elem.left, elem.right):
                if isinstance(side, StreamStateElement):
                    add_ref(side.stream, 1)
        elif isinstance(elem, StreamStateElement):
            n_slots += 1
            add_ref(elem.stream, 1)

    ref_n = [0]

    def add_ref(s: SingleInputStream, cap: int) -> None:
        schema = sym.streams.get(s.stream_id)
        i = ref_n[0]
        ref_n[0] += 1
        tensors.append(TensorSpec(f"cap{i}.n", (T,), "int32"))
        tensors.append(TensorSpec(f"cap{i}.ts", (T, cap), "int64"))
        tensors.extend(
            TensorSpec(f"cap{i}.{t.lane}", (T, cap), t.dtype)
            for t in _schema_tensors(schema, 1)
        )

    walk(stream.state)
    return OperatorCost(
        "pattern",
        f"{stream.type.value} {n_slots} slot(s), {ref_n[0]} ref(s), T={T}",
        tensors,
        _SEL["pattern"],
        getattr(stream, "line", None), getattr(stream, "col", None),
    )


def _pattern_scheduler_armed(stream: StateInputStream) -> bool:
    """Mirrors PatternProgram.needs_scheduler: absent atoms with waiting
    times arm host timers."""
    def walk(elem) -> bool:
        if isinstance(elem, AbsentStreamStateElement):
            return elem.waiting_time_ms is not None
        if isinstance(elem, CountStateElement):
            return walk(elem.stream)
        if isinstance(elem, NextStateElement):
            return walk(elem.state) or walk(elem.next)
        if isinstance(elem, EveryStateElement):
            return walk(elem.state)
        if isinstance(elem, LogicalStateElement):
            return walk(elem.left) or walk(elem.right)
        return False

    return walk(stream.state)


def _tail_variants(K: int) -> list:
    """Distinct smaller-K variants core/ingest.py _chunk_K can compile: the
    powers of two in [2, K)."""
    out = []
    k = 2
    while k < K:
        out.append(k)
        k *= 2
    return out


# ---------------------------------------------------------------------------
# per-app computation
# ---------------------------------------------------------------------------


def iter_query_entries(app: SiddhiApp):
    """Yield (qid, query, in_partition) for every execution element, ids
    matching query_api.execution.assign_execution_ids — the ONE query walk
    shared by the cost model, the lints, and the fusion planner."""
    for ent in assign_execution_ids(app):
        if ent[0] == "query":
            yield ent[1], ent[2], False
        else:
            for qid, q in ent[3]:
                yield qid, q, True


def produced_streams(app: SiddhiApp) -> set:
    """Outer stream ids some query inserts into (re-published batches)."""
    produced: set = set()
    for _qid, q, _in_part in iter_query_entries(app):
        target = getattr(q.output_stream, "target", None)
        if target and not getattr(q.output_stream, "is_inner", False):
            produced.add(target)
    return produced


def _hint_lane_bytes(hint, t: AttrType) -> Optional[int]:
    """Narrowed wire bytes/row one declared-or-inferred hint buys a lane
    of declared type `t`, or None when the hint does not shrink it.
    Mirrors core/wire.py lane widths without the amortized headers (the
    cost model predicts per-row bytes, not per-chunk)."""
    wide = _NBYTES[t or AttrType.LONG]
    if hint is None or t not in (AttrType.INT, AttrType.LONG,
                                 AttrType.STRING, AttrType.OBJECT):
        return None
    if hint[0] == "range" and t in (AttrType.INT, AttrType.LONG):
        lo, hi = int(hint[1]), int(hint[2])
        for width, bound in ((1, 1 << 7), (2, 1 << 15), (4, 1 << 31)):
            if width < wide and -bound <= lo and hi < bound:
                return width
        return None
    if hint[0] == "dict":
        width = 1 if int(hint[1]) <= 256 else 2
        return width if width < wide else None
    if hint[0] == "delta" and t in (AttrType.INT, AttrType.LONG):
        try:
            width = int(getattr(hint[1], "itemsize", 2))
        except (TypeError, ValueError):
            width = 2
        return width if width < wide else None
    return None


def compute_costs(app: SiddhiApp, sym=None, values=None) -> AppCostModel:
    """Build the full static cost model for `app`. Never raises on bad apps:
    unresolvable pieces degrade to empty/None entries. With `values` (a
    ValueAnalysis from analysis/values.py), state tensors size at proven
    narrowed widths, filter selectivities refine from interval overlap,
    and wire-byte predictions price declared @app:wire contracts AND
    inferred encoders instead of full declared widths."""
    from siddhi_tpu.analysis.symbols import build_symbols

    if sym is None:
        sym = build_symbols(app, [])

    B = _capacity_annotation(app, "app:batch", DEFAULT_BATCH)
    K = _capacity_annotation(app, "app:ingestChunk", DEFAULT_CHUNK_BATCHES)
    K = max(2, K)
    model = AppCostModel(app.name, B, K)

    # declared @app:wire contracts price the wire even WITHOUT a value
    # analysis, and their range hints become interval facts for tensor
    # narrowing + filter selectivity below; inferred hints (seeded from
    # the declared ones, so at least as tight) overlay both
    wire_hints: dict = {}
    declared_facts: dict = {}
    try:
        from siddhi_tpu.analysis.values import ValueFact
        from siddhi_tpu.core.wire import parse_wire_hints

        declared = parse_wire_hints(
            find_annotation(app.annotations, "app:wire")
        )
        wire_hints = dict(declared)
        for (sid, col), hint in declared.items():
            if hint[0] != "range":
                continue
            schema = sym.streams.get(sid)
            atype = schema.get(col) if schema else None
            declared_facts.setdefault(sid, {})[col] = ValueFact(
                lo=int(hint[1]), hi=int(hint[2]), atype=atype
            )
    except Exception:  # pragma: no cover - defect guard
        declared_facts = {}
    if values is not None:
        try:
            from siddhi_tpu.analysis.values import infer_wire_hints

            wire_hints.update(infer_wire_hints(values, sym))
        except Exception:  # pragma: no cover - defect guard
            pass

    produced = produced_streams(app)
    for qid, q, in_part in iter_query_entries(app):
        model.queries[qid] = _query_cost(
            q, qid, app, sym, B, in_part, produced, values,
            declared_facts=declared_facts,
        )

    for sid, schema in sym.streams.items():
        consumers = [
            qc for qc in model.queries.values() if sid in qc.consumed_streams
        ]
        if not consumers:
            continue
        row_bytes = None
        if schema is not None:
            row_bytes = 8  # int64 timestamp lane
            for name, t in schema.items():
                narrowed = _hint_lane_bytes(wire_hints.get((sid, name)), t)
                row_bytes += (
                    narrowed if narrowed is not None
                    else _NBYTES[t or AttrType.LONG]
                )
        has_interned = schema is not None and any(
            t in (AttrType.STRING, AttrType.OBJECT) for t in schema.values()
        )
        model.streams[sid] = StreamCost(
            sid,
            wire_row_bytes=row_bytes,
            chunk_batches=K,
            tail_variants=_tail_variants(K),
            narrow_rebuild_hazard=has_interned,
        )
    return model


def _query_cost(
    q: Query,
    qid: str,
    app: SiddhiApp,
    sym,
    B: int,
    in_partition: bool,
    produced: set,
    values=None,
    declared_facts: Optional[dict] = None,
) -> QueryCost:
    stream = q.input_stream
    operators: list[OperatorCost] = []
    programs: list[ProgramCost] = []
    consumed: list[str] = []
    armed = False
    kind = "single"

    def stream_facts(sid: str) -> Optional[dict]:
        # declared @app:wire range facts as the base; the value analysis
        # (when supplied) overlays them with its at-least-as-tight facts
        base = dict(declared_facts.get(sid, {})) if declared_facts else {}
        if values is not None:
            facts = values.facts_for(sid)
            if facts:
                base.update(facts)
        return base or None

    def step_causes(extra_shapes: int) -> dict:
        causes = {"first_compile": 1}
        if extra_shapes:
            causes["shape_change"] = extra_shapes
        return causes

    if isinstance(stream, SingleInputStream):
        schema = sym.streams.get(stream.stream_id) or sym.windows.get(
            stream.stream_id
        )
        consumed.append(stream.stream_id)
        ops, armed = _source_operators(
            stream, schema, qid, stream_facts(stream.stream_id)
        )
        operators.extend(ops)
        extra = (1 if armed else 0) + (
            1 if stream.stream_id in produced and B != 64 else 0
        )
        programs.append(ProgramCost(
            f"query.{qid}", input_rows=B,
            predicted_causes=step_causes(extra),
        ))
    elif isinstance(stream, JoinInputStream):
        kind = "join"
        jc = _capacity_annotation(
            app, "app:joinCapacity", DEFAULT_JOIN_CAPACITY
        )
        for side, s in (("left", stream.left), ("right", stream.right)):
            sid = s.stream_id
            is_stream = sid in sym.streams or sid in sym.windows
            schema = sym.streams.get(sid) or sym.tables.get(sid) \
                or sym.windows.get(sid)
            if sid in sym.streams:
                consumed.append(sid)
            ops, side_armed = _source_operators(
                s, schema, qid, stream_facts(sid)
            )
            armed = armed or side_armed
            # a join side buffers its window content at join capacity
            win = [o for o in ops if o.op.startswith("window")]
            operators.extend(ops)
            if is_stream:
                side_tensors = _schema_tensors(
                    schema, jc, prefix="buf", facts=stream_facts(sid)
                )
                operators.append(OperatorCost(
                    f"join:{side}",
                    f"side buffer cap={jc}"
                    + (f" ({win[0].detail})" if win else ""),
                    side_tensors
                    + [TensorSpec("buf.ts", (jc,), "int64")],
                    None,
                    getattr(s, "line", None), getattr(s, "col", None),
                ))
                extra = (1 if side_armed else 0) + (
                    1 if sid in produced and B != 64 else 0
                )
                programs.append(ProgramCost(
                    f"query.{qid}[{side}]", input_rows=B,
                    predicted_causes=step_causes(extra),
                ))
        operators.append(OperatorCost(
            "join", stream.join_type.value, [], _SEL["join"],
            getattr(stream, "line", None), getattr(stream, "col", None),
        ))
    elif isinstance(stream, StateInputStream):
        kind = "pattern"
        operators.append(_pattern_cost(stream, sym, app, qid))
        armed = _pattern_scheduler_armed(stream)
        sids = sorted({
            s.stream_id for s in iter_state_streams(stream.state)
        })
        consumed.extend(sids)
        for sid in sids:
            extra = (1 if armed else 0) + (
                1 if sid in produced and B != 64 else 0
            )
            programs.append(ProgramCost(
                f"query.{qid}[{sid}]", input_rows=B,
                predicted_causes=step_causes(extra),
            ))

    sel = q.selector
    if sel is not None and not sel.select_all:
        if sel.group_by:
            gcap = _capacity_annotation(
                app, "app:groupCapacity", DEFAULT_GROUP_CAPACITY
            )
            operators.append(OperatorCost(
                "groupby",
                f"{len(sel.group_by)} key(s), cap={gcap}",
                [
                    TensorSpec("keys", (gcap, len(sel.group_by)), "int64"),
                    TensorSpec("used", (gcap,), "bool"),
                ],
                None,
                getattr(sel, "line", None), getattr(sel, "col", None),
            ))
        if sel.having is not None:
            operators.append(OperatorCost(
                "having", "having", [], _SEL["having"],
                getattr(sel, "line", None), getattr(sel, "col", None),
            ))

    return QueryCost(
        qid=qid,
        kind=kind,
        operators=operators,
        programs=programs,
        scheduler_armed=armed,
        rate_limited=q.output_rate is not None,
        in_partition=in_partition,
        consumed_streams=consumed,
    )


# ---------------------------------------------------------------------------
# aggregation state estimate (definitions, not queries)
# ---------------------------------------------------------------------------


def aggregation_state_bytes(ad, app: SiddhiApp) -> Optional[int]:
    """Closed-bucket tables per duration × group capacity × base columns —
    a coarse upper bound mirroring core/aggregation.py table sizing."""
    durations = ad.bucket_durations()
    if not durations or ad.selector is None:
        return None
    groups = _capacity_annotation(app, "app:aggGroupCapacity", DEFAULT_AGG_GROUPS)
    n_base = max(1, len(ad.selector.selection_list)) + len(ad.selector.group_by)
    return len(durations) * groups * n_base * 8  # widest lanes (int64/f64 pairs)


# ---------------------------------------------------------------------------
# lints: SA120 / SA121 / SA122
# ---------------------------------------------------------------------------


def check_costs(
    app: SiddhiApp, sym, diags: list,
    model: Optional[AppCostModel] = None, values=None,
) -> AppCostModel:
    """Run the cost lints; returns the model so callers reuse it."""
    if model is None:
        model = compute_costs(app, sym, values)
    budget = state_budget_bytes()

    # SA120: every with no within, anywhere in a pattern/sequence
    for qid, q, _in_part in iter_query_entries(app):
        stream = q.input_stream
        if isinstance(stream, StateInputStream):
            _check_unbounded_every(stream, qid, diags)

    # SA121: oversized operator state (windows, patterns, join buffers)
    for qid, qc in sorted(model.queries.items()):
        for op in qc.operators:
            if op.state_bytes > budget:
                diags.append(Diagnostic(
                    "SA121",
                    f"{op.op} state is ~{_fmt_bytes(op.state_bytes)} on "
                    f"device ({op.detail}), over the "
                    f"{_fmt_bytes(budget)} budget "
                    f"(raise ${_BUDGET_MB_ENV} or shrink the window)",
                    op.line, op.col, severity=WARNING, query=qid,
                ))

    # SA121: named window defined with no window type = unbounded retention
    for wid, wd in app.window_definitions.items():
        if wd.window is None:
            diags.append(Diagnostic(
                "SA121",
                f"named window '{wid}' has no window type: rows are never "
                "expired (unbounded retention) — give it a bounded window, "
                "e.g. length(N) or time(T)",
                getattr(wd, "line", None), getattr(wd, "col", None),
                severity=WARNING,
            ))

    # SA121: aggregation bucket tables over budget
    for aid, ad in app.aggregation_definitions.items():
        est = aggregation_state_bytes(ad, app)
        if est is not None and est > budget:
            diags.append(Diagnostic(
                "SA121",
                f"aggregation '{aid}' bucket tables are "
                f"~{_fmt_bytes(est)} on device, over the "
                f"{_fmt_bytes(budget)} budget",
                getattr(ad, "line", None), getattr(ad, "col", None),
                severity=WARNING,
            ))

    # SA122: tail-variant ladder explosion on the fused chunk program
    tails = _tail_variants(model.chunk_batches)
    if len(tails) >= _TAIL_CHURN and model.streams:
        ann = find_annotation(app.annotations, "app:ingestChunk")
        diags.append(Diagnostic(
            "SA122",
            f"@app:ingestChunk(size='{model.chunk_batches}') predicts "
            f"{len(tails)} tail-variant compiles of every fused chunk "
            "program (core/ingest.py _chunk_K power-of-two ladder) — each "
            "is a full XLA compile mid-traffic; lower the chunk size or "
            "pre-warm with SIDDHI_TPU_PREWARM_TAIL=1",
            getattr(ann, "line", None), getattr(ann, "col", None),
            severity=WARNING,
        ))

    # SA133/SA138: h2d-dominant wide column — a LONG column with no
    # @app:wire encoding hint that alone accounts for >= half the stream's
    # estimated wire bytes/event on a consumed (h2d-riding) stream. SA133
    # (add a hint) only when value analysis CANNOT prove the lane
    # encodable; when it can, SA138 says inference already compacts it.
    _check_wire_dominance(app, sym, model, diags, values)

    # SA122: @app:batch != 64 downstream of a query insert (re-published
    # slices arrive <= 64 rows: a second shape signature per program)
    if model.batch_size != 64:
        produced = produced_streams(app)
        for qid, qc in sorted(model.queries.items()):
            hit = sorted(set(qc.consumed_streams) & produced)
            if hit:
                diags.append(Diagnostic(
                    "SA122",
                    f"@app:batch(size='{model.batch_size}') with "
                    f"query-produced input '{hit[0]}': re-published batches "
                    "arrive in <=64-row slices, so this query's program "
                    "compiles a second shape signature "
                    "(predicted shape_change recompiles)",
                    None, None, severity=WARNING, query=qid,
                ))
    return model


def _check_wire_dominance(
    app: SiddhiApp, sym, model: AppCostModel, diags: list, values=None
) -> None:
    """SA133/SA138 (see check_costs). Skipped when the app opts out via
    `@app:wire(disable='true')` — the user already declined the wire
    layer, so the hint would be noise. Dominance is judged on the
    DECLARED-only spec (the wide lane is wide until someone encodes it);
    the verdict then splits on whether value analysis proves the lane
    encodable. Specs come from the SAME shared preamble the FusionPlan
    wire section uses (core/wire.py app_wire_specs), at the model's real
    batch size."""
    from siddhi_tpu.core.wire import (
        _hint_entry,
        app_wire_specs,
        estimate_wire_bytes,
        lane_bytes_per_row,
    )

    disabled, specs = app_wire_specs(
        app, sym.streams, sorted(model.streams), model.batch_size
    )
    if disabled:
        return
    inferred: dict = {}
    if values is not None:
        try:
            from siddhi_tpu.analysis.values import infer_wire_hints

            inferred = infer_wire_hints(values, sym)
        except Exception:  # pragma: no cover - defect guard
            inferred = {}
    _HINT_WORD = {"range": "bounded", "dict": "low-cardinality",
                  "delta": "monotone"}
    for sid, (attrs, spec) in specs.items():
        enc = spec.encodings if spec is not None else {}
        total = max(
            estimate_wire_bytes(attrs, spec, capacity=model.batch_size), 1
        )
        d = app.stream_definitions.get(sid)
        for name, t in attrs:
            if t is not AttrType.LONG or name in enc:
                continue
            # STRICTLY dominant: the one wide lane outweighs everything
            # else on the wire combined (a 50/50 split stays quiet — the
            # false-positive net is the whole test corpus)
            if 8.0 / total <= 0.5:
                continue
            hint = inferred.get((sid, name))
            entry = None
            if hint is not None:
                import numpy as np

                entry = _hint_entry(hint, t, np.dtype(np.int64))
                if entry is not None and lane_bytes_per_row(
                    name, np.dtype(np.int64), entry, model.batch_size
                ) >= 8:
                    entry = None
            if entry is not None:
                diags.append(Diagnostic(
                    "SA138",
                    f"stream '{sid}': LONG column '{name}' dominates the "
                    f"h2d wire (8 of ~{total} B/event), and value "
                    f"analysis proves it {_HINT_WORD[hint[0]]} — wire "
                    f"inference {hint[0]}-encodes it with no annotation",
                    getattr(d, "line", None), getattr(d, "col", None),
                    severity=WARNING,
                ))
                continue
            diags.append(Diagnostic(
                "SA133",
                f"stream '{sid}': LONG column '{name}' rides the h2d wire "
                f"full-width and dominates it (8 of ~{total} B/event) — "
                f"declare @app:wire(range.{sid}.{name}='lo..hi') or "
                f"delta.{sid}.{name}='int16', or use interned strings",
                getattr(d, "line", None), getattr(d, "col", None),
                severity=WARNING,
            ))


def _check_unbounded_every(
    stream: StateInputStream, qid: str, diags: list
) -> None:
    """SA120: an `every` pattern with no `within` bound ANYWHERE — neither
    on the whole pattern nor on any state element. A within on a later
    element still bounds the every's forked tokens (they must traverse
    that slot, whose bound kills them — core/pattern.py _min_within), so
    only the fully-unbounded shape warns: there, partial-match tokens are
    never expired, the fixed token table (@app:patternCapacity) fills,
    and further matches silently drop."""
    if stream.within_ms is not None:
        return
    if _subtree_has_within(stream.state):
        return
    every = _find_first_every(stream.state)
    if every is None:
        return
    line = getattr(every, "line", None) or getattr(stream, "line", None)
    col = getattr(every, "col", None) or getattr(stream, "col", None)
    diags.append(Diagnostic(
        "SA120",
        "'every' with no 'within' bound anywhere in the pattern: "
        "partial-match tokens fork per match and are never expired, so "
        "the fixed token table (@app:patternCapacity) fills and further "
        "matches drop — add 'within <time>'",
        line, col, severity=WARNING, query=qid,
    ))


def _find_first_every(elem):
    if isinstance(elem, EveryStateElement):
        return elem
    for child in ("state", "next", "left", "right", "stream"):
        c = getattr(elem, child, None)
        if c is None or isinstance(c, SingleInputStream):
            continue
        found = _find_first_every(c)
        if found is not None:
            return found
    return None


def _subtree_has_within(elem) -> bool:
    if getattr(elem, "within_ms", None) is not None:
        return True
    for child in ("state", "next", "left", "right", "stream"):
        c = getattr(elem, child, None)
        if c is not None and not isinstance(c, SingleInputStream) \
                and _subtree_has_within(c):
            return True
    return False
