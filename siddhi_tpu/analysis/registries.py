"""Window / stream-function name + argument validation.

Mirrors the dispatch tables of `core/windows.py::make_window` and
`core/stream_function.py::make_stream_function` plus the extension registry
(`core/extension.py`), without constructing any runtime stage. Extension
windows/stream functions validate the name only — their parameter contracts
live in the extension factories.
"""

from __future__ import annotations

from typing import Optional

from siddhi_tpu.core.extension import lookup
from siddhi_tpu.core.types import AttrType
from siddhi_tpu.query_api.definition import WindowSpec
from siddhi_tpu.query_api.expression import Constant, Expression, Variable

from siddhi_tpu.analysis.diagnostics import Diagnostic

# builtin window name -> (min_args, max_args) with per-window extra checks
BUILTIN_WINDOWS = {
    "length": (1, 1),
    "time": (1, 1),
    "timelength": (2, 2),
    "externaltime": (2, 2),
    "lengthbatch": (1, 1),
    "timebatch": (1, 2),
    "externaltimebatch": (2, 4),
    "sort": (1, None),
    "frequent": (1, None),
    "lossyfrequent": (1, None),
    "cron": (1, 1),
}

# which builtin window parameter positions must be constant integers/times
_INT_PARAMS = {
    "length": (0,),
    "time": (0,),
    "timelength": (0, 1),
    "externaltime": (1,),
    "lengthbatch": (0,),
    "timebatch": (0, 1),
    "externaltimebatch": (1, 2, 3),
    "sort": (0,),
    "frequent": (0,),
}

# parameter positions that must be an attribute of the stream (external time)
_ATTR_PARAMS = {
    "externaltime": (0,),
    "externaltimebatch": (0,),
}


def _window_key(spec: WindowSpec) -> str:
    return (
        spec.name.lower()
        if spec.namespace is None
        else f"{spec.namespace}:{spec.name}"
    )


def check_window(
    spec: WindowSpec,
    checker,
    scope,
    diags: list[Diagnostic],
    query: Optional[str],
) -> None:
    """Validate one `#window.name(...)` / window-definition spec."""
    name = _window_key(spec)

    def diag(code: str, msg: str, node=None) -> None:
        node = node if node is not None else spec
        diags.append(Diagnostic(
            code, msg,
            getattr(node, "line", None), getattr(node, "col", None),
            query=query,
        ))

    if name not in BUILTIN_WINDOWS:
        if lookup("window", name) is not None:
            for p in spec.parameters:
                checker.infer_no_agg(p, scope)
            return
        diag("SA301", f"unknown window type '{spec.name}'")
        return

    lo, hi = BUILTIN_WINDOWS[name]
    n = len(spec.parameters)
    if n < lo or (hi is not None and n > hi):
        expect = f"{lo}" if hi == lo else (f"{lo}+" if hi is None else f"{lo}-{hi}")
        diag(
            "SA302",
            f"window '{spec.name}' takes {expect} parameter(s), got {n}",
        )
        return

    for i in _INT_PARAMS.get(name, ()):
        if i >= n:
            continue
        p = spec.parameters[i]
        if not isinstance(p, Constant):
            diag(
                "SA302",
                f"window '{spec.name}': parameter {i} must be a constant "
                "integer or time value",
                p,
            )
        elif not isinstance(p.value, (int, float)) or isinstance(p.value, bool):
            diag(
                "SA302",
                f"window '{spec.name}': parameter {i} must be a constant "
                f"integer or time value, got {p.value!r}",
                p,
            )

    for i in _ATTR_PARAMS.get(name, ()):
        if i >= n:
            continue
        p = spec.parameters[i]
        if not isinstance(p, Variable):
            diag(
                "SA302",
                f"window '{spec.name}': parameter {i} must be an attribute",
                p,
            )
            continue
        t = checker.resolve_variable(p, scope)
        if t is not None and t not in (AttrType.INT, AttrType.LONG):
            diag(
                "SA302",
                f"window '{spec.name}': external time attribute "
                f"'{p.attribute}' must be INT/LONG, got {t!r}",
                p,
            )

    if name == "cron":
        p = spec.parameters[0]
        if not (isinstance(p, Constant) and isinstance(p.value, str)):
            diag(
                "SA302",
                "window 'cron': parameter 0 must be a constant cron string",
                p if isinstance(p, Expression) else None,
            )

    if name == "sort":
        _check_sort_keys(spec, spec.parameters[1:], checker, scope, diag)
    elif name == "frequent":
        for p in spec.parameters[1:]:
            if not isinstance(p, Variable):
                diag("SA302", "window 'frequent': keys must be attributes", p)
            else:
                checker.resolve_variable(p, scope)
    elif name == "lossyfrequent":
        rest = spec.parameters[1:]
        if rest and isinstance(rest[0], Constant) and not isinstance(
            rest[0].value, str
        ):
            rest = rest[1:]  # optional error-bound constant
        for p in rest:
            if not isinstance(p, Variable):
                diag("SA302", "window 'lossyFrequent': keys must be attributes", p)
            else:
                checker.resolve_variable(p, scope)


def _check_sort_keys(spec, params, checker, scope, diag) -> None:
    i = 0
    while i < len(params):
        p = params[i]
        if not isinstance(p, Variable):
            diag(
                "SA302",
                "window 'sort': parameters after the length must be "
                "attribute [, 'asc'|'desc'] pairs",
                p,
            )
            return
        checker.resolve_variable(p, scope)
        if (
            i + 1 < len(params)
            and isinstance(params[i + 1], Constant)
            and str(params[i + 1].value).lower() in ("asc", "desc")
        ):
            i += 1
        i += 1


# stream functions: builtin name -> (handler) — returns the appended output
# attrs, or OPEN (None) when unknown (extension), mirroring
# stream_function.make_stream_function
def check_stream_function(
    handler,
    checker,
    scope,
    diags: list[Diagnostic],
    query: Optional[str],
):
    """Validate a `#ns:name(...)` handler. Returns (ok, new_attrs) where
    new_attrs is a dict of appended attributes, or None when the function is
    an extension whose output attributes are unknowable statically."""
    name = (
        f"{handler.namespace}:{handler.name}"
        if handler.namespace
        else handler.name
    ).lower()

    def diag(code: str, msg: str, node=None) -> None:
        node = node if node is not None else handler
        diags.append(Diagnostic(
            code, msg,
            getattr(node, "line", None), getattr(node, "col", None),
            query=query,
        ))

    if name == "log":
        return True, {}

    if name == "pol2cart":
        if len(handler.parameters) not in (2, 3):
            diag("SA302", "pol2Cart(theta, rho[, z]) needs 2-3 arguments")
        new = {"x": AttrType.DOUBLE, "y": AttrType.DOUBLE}
        for p in handler.parameters:
            t = checker.infer_no_agg(p, scope)
            if t is not None and t not in (
                AttrType.INT, AttrType.LONG, AttrType.FLOAT, AttrType.DOUBLE,
            ):
                diag("SA302", f"pol2Cart arguments must be numeric, got {t!r}", p)
        if len(handler.parameters) > 2:
            new["z"] = AttrType.DOUBLE
        return True, new

    if lookup("stream_function", name) is not None or lookup(
        "stream_processor", name
    ) is not None:
        for p in handler.parameters:
            checker.infer_no_agg(p, scope)
        return True, None  # extension: appended attrs unknown

    diag("SA303", f"unknown stream function '#{name}'")
    return False, {}
