"""Symbol table: the definitions pass of the semantic analyzer.

Collects every name a query can reference — streams (plus @OnError fault
streams and trigger streams), tables, named windows, aggregations, and script
functions — mirroring what `SiddhiAppRuntime.__init__` registers at creation
time (app_runtime.py stream_schemas / tables / named_windows / aggregations).

A schema is a dict `attr -> AttrType | None`; the whole schema may instead be
`OPEN` (None) meaning "attributes unknown" — e.g. downstream of an extension
stream function — in which case attribute checks are skipped rather than
guessed at.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from siddhi_tpu.core.types import AttrType
from siddhi_tpu.query_api.annotation import find_annotation
from siddhi_tpu.query_api.siddhi_app import SiddhiApp

from siddhi_tpu.analysis.diagnostics import Diagnostic

# schema type: dict[attr] -> AttrType | None (None = unknown attr type)
Schema = dict


@dataclasses.dataclass
class SymbolTable:
    streams: dict[str, Optional[Schema]] = dataclasses.field(default_factory=dict)
    tables: dict[str, Optional[Schema]] = dataclasses.field(default_factory=dict)
    windows: dict[str, Optional[Schema]] = dataclasses.field(default_factory=dict)
    aggregations: dict[str, Optional[Schema]] = dataclasses.field(default_factory=dict)
    # aggregation definitions by id (within/per clause checks need the
    # declared time_period durations)
    aggregation_defs: dict = dataclasses.field(default_factory=dict)
    # script-defined functions: name -> return AttrType
    functions: dict[str, AttrType] = dataclasses.field(default_factory=dict)
    # streams declaring @OnError(action='STREAM') (fault stream '!S' exists)
    fault_parents: set = dataclasses.field(default_factory=set)
    # streams carrying a @source / declared triggers (dataflow producers)
    sourced: set = dataclasses.field(default_factory=set)
    # streams carrying a @sink (dataflow consumers)
    sinked: set = dataclasses.field(default_factory=set)

    def consumable(self, stream_id: str) -> Optional[Schema]:
        """Schema for a `from X` source (stream, fault stream, or window);
        KeyError semantics are the caller's job — returns a sentinel miss."""
        if stream_id in self.streams:
            return self.streams[stream_id]
        if stream_id in self.windows:
            return self.windows[stream_id]
        raise KeyError(stream_id)

    def describe(self, stream_id: str) -> Optional[str]:
        """What a name IS, for better undefined-stream messages."""
        if stream_id in self.tables:
            return "table"
        if stream_id in self.aggregations:
            return "aggregation"
        return None


def _attrs_schema(definition, diags: list[Diagnostic], what: str) -> Schema:
    schema: Schema = {}
    for a in definition.attributes:
        if a.name in schema:
            diags.append(Diagnostic(
                "SA109",
                f"duplicate attribute '{a.name}' in {what} '{definition.id}'",
                getattr(a, "line", None), getattr(a, "col", None),
            ))
        schema[a.name] = a.type
    return schema


def _check_pipeline_annotation(
    sid: str, d, ann, diags: list[Diagnostic]
) -> None:
    """Validate `@pipeline(depth='N', disable='true|false')` — the fused
    ingest pipeline's stream-level config. One SA112 per malformed element,
    using the SAME rule set the runtime resolver enforces
    (core/pipeline.py iter_pipeline_annotation_problems)."""
    from siddhi_tpu.core.pipeline import iter_pipeline_annotation_problems

    line, col = getattr(d, "line", None), getattr(d, "col", None)
    for problem in iter_pipeline_annotation_problems(ann):
        diags.append(Diagnostic(
            "SA112", f"stream '{sid}': {problem}", line, col,
        ))


def _check_flight_annotation(
    sid: str, d, ann, diags: list[Diagnostic]
) -> None:
    """Validate `@flightRecorder(size='N')` — the per-junction last-N-events
    ring. One SA114 per malformed element, using the SAME rule set the
    runtime resolver enforces (observability/flight.py)."""
    from siddhi_tpu.observability.flight import (
        iter_flight_annotation_problems,
    )

    line, col = getattr(d, "line", None), getattr(d, "col", None)
    for problem in iter_flight_annotation_problems(ann):
        diags.append(Diagnostic(
            "SA114", f"stream '{sid}': {problem}", line, col,
        ))


def _check_fuse_annotation(app: SiddhiApp, diags: list[Diagnostic]) -> None:
    """Validate `@app:fuse(disable='true|false')` — the whole-graph fusion
    escape hatch. One SA125 per malformed element, using the SAME rule set
    the runtime resolver raises on (core/fusion_exec.py
    iter_fuse_annotation_problems), so the two can never drift."""
    ann = find_annotation(app.annotations, "app:fuse")
    if ann is None:
        return
    from siddhi_tpu.core.fusion_exec import iter_fuse_annotation_problems

    for problem in iter_fuse_annotation_problems(ann):
        diags.append(Diagnostic("SA125", problem))


def _check_shard_annotation(app: SiddhiApp, diags: list[Diagnostic]) -> None:
    """Validate `@app:shard(devices='N', axis='part|batch|auto')` — the
    first-class sharded-execution mode. One SA129 per malformed element,
    using the SAME rule set the runtime resolver raises on
    (parallel/shard.py iter_shard_annotation_problems), so the two can
    never drift."""
    ann = find_annotation(app.annotations, "app:shard")
    if ann is None:
        return
    from siddhi_tpu.parallel.shard import iter_shard_annotation_problems

    for problem in iter_shard_annotation_problems(ann):
        diags.append(Diagnostic("SA129", problem))


def _check_lineage_annotation(app: SiddhiApp, diags: list[Diagnostic]) -> None:
    """Validate `@app:lineage(capacity='N', mode='full|sample',
    sample.every='K')` — event lineage & provenance. One SA131 per
    malformed element, using the SAME rule set the runtime resolver raises
    on (observability/lineage.py iter_lineage_annotation_problems), so the
    two can never drift."""
    ann = find_annotation(app.annotations, "app:lineage")
    if ann is None:
        return
    from siddhi_tpu.observability.lineage import (
        iter_lineage_annotation_problems,
    )

    for problem in iter_lineage_annotation_problems(ann):
        diags.append(Diagnostic("SA131", problem))


def _check_wire_annotation(
    app: SiddhiApp, sym: SymbolTable, diags: list[Diagnostic]
) -> None:
    """Validate `@app:wire(disable='true|false',
    range/dict/delta.<stream>.<col>='...')` — the compact wire-encoding
    layer's config. One SA132 per malformed element, using the SAME rule
    set the runtime resolver raises on (core/wire.py
    iter_wire_annotation_problems); the analyzer additionally passes the
    symbol table so hint targets are checked for existence and
    encoder/type compatibility."""
    ann = find_annotation(app.annotations, "app:wire")
    if ann is None:
        return
    from siddhi_tpu.core.wire import iter_wire_annotation_problems

    for problem in iter_wire_annotation_problems(ann, streams=sym.streams):
        diags.append(Diagnostic("SA132", problem))


def _check_watermark_annotation(app: SiddhiApp, diags: list[Diagnostic]) -> None:
    """Validate `@app:watermark(bound='...', idle.timeout='...',
    late.policy='drop|stream|apply', allowed.lateness='...')` — the
    event-time robustness layer. One SA134 per malformed element, using
    the SAME rule set the runtime resolver raises on (core/watermark.py
    iter_watermark_annotation_problems), so the two can never drift."""
    ann = find_annotation(app.annotations, "app:watermark")
    if ann is None:
        return
    from siddhi_tpu.core.watermark import iter_watermark_annotation_problems

    for problem in iter_watermark_annotation_problems(ann):
        diags.append(Diagnostic("SA134", problem))


def _check_supervision_annotations(
    app: SiddhiApp, diags: list[Diagnostic]
) -> None:
    """Validate the supervised-runtime app annotations — `@app:persist`
    (SA126), `@app:restart` (SA127), `@app:admission` (SA128) — using the
    SAME rule sets the runtime resolvers raise on (core/supervision.py,
    core/admission.py), so analyzer and runtime can never drift."""
    from siddhi_tpu.core.admission import iter_admission_annotation_problems
    from siddhi_tpu.core.supervision import (
        iter_persist_annotation_problems,
        iter_restart_annotation_problems,
    )

    for name, code, rules in (
        ("app:persist", "SA126", iter_persist_annotation_problems),
        ("app:restart", "SA127", iter_restart_annotation_problems),
        ("app:admission", "SA128", iter_admission_annotation_problems),
    ):
        ann = find_annotation(app.annotations, name)
        if ann is None:
            continue
        for problem in rules(ann):
            diags.append(Diagnostic(code, problem))


def _check_blackbox_annotation(app: SiddhiApp, diags: list[Diagnostic]) -> None:
    """Validate `@app:blackbox(window='...', triggers='...', keep='N',
    ring='N', dir='...', checkpoint.interval='...', debounce='...')` — the
    black-box incident recorder. One SA140 per malformed element, using
    the SAME rule set the runtime resolver raises on
    (observability/blackbox.py iter_blackbox_annotation_problems), so the
    two can never drift."""
    ann = find_annotation(app.annotations, "app:blackbox")
    if ann is None:
        return
    from siddhi_tpu.observability.blackbox import (
        iter_blackbox_annotation_problems,
    )

    for problem in iter_blackbox_annotation_problems(ann):
        diags.append(Diagnostic("SA140", problem))


def _apply_selfmon_annotation(
    app: SiddhiApp, sym: SymbolTable, diags: list[Diagnostic]
) -> None:
    """`@app:selfmon(interval='...')`: validate (SA113, same rule set as
    the runtime resolver — observability/selfmon.py) and inject the
    engine-fed `SelfMonitorStream` system definition so queries over it
    resolve — mirroring what `SiddhiAppRuntime.__init__` registers."""
    ann = find_annotation(app.annotations, "app:selfmon")
    if ann is None:
        return
    from siddhi_tpu.observability.selfmon import (
        SELFMON_STREAM_ID,
        iter_selfmon_annotation_problems,
        selfmon_attrs,
    )

    problems = list(iter_selfmon_annotation_problems(
        ann, defined_streams=app.stream_definitions
    ))
    for problem in problems:
        diags.append(Diagnostic("SA113", problem))
    if SELFMON_STREAM_ID not in sym.streams:
        sym.streams[SELFMON_STREAM_ID] = dict(selfmon_attrs())
        sym.sourced.add(SELFMON_STREAM_ID)  # engine-fed, never query-fed


def _apply_slo_annotation(
    app: SiddhiApp, sym: SymbolTable, diags: list[Diagnostic]
) -> None:
    """`@app:slo(p99.latency.ms='...', ...)`: validate (SA139, same rule
    set as the runtime resolver — observability/slo.py) and inject the
    engine-fed `SloAlertStream` system definition so alert subscribers
    resolve — the selfmon precedent."""
    ann = find_annotation(app.annotations, "app:slo")
    if ann is None:
        return
    from siddhi_tpu.observability.slo import (
        SLO_STREAM_ID,
        iter_slo_annotation_problems,
        slo_attrs,
    )

    problems = list(iter_slo_annotation_problems(
        ann, defined_streams=app.stream_definitions
    ))
    for problem in problems:
        diags.append(Diagnostic("SA139", problem))
    if SLO_STREAM_ID not in sym.streams:
        sym.streams[SLO_STREAM_ID] = dict(slo_attrs())
        sym.sourced.add(SLO_STREAM_ID)  # engine-fed, never query-fed


def build_symbols(app: SiddhiApp, diags: list[Diagnostic]) -> SymbolTable:
    sym = SymbolTable()

    for sid, d in app.stream_definitions.items():
        sym.streams[sid] = _attrs_schema(d, diags, "stream")
        if find_annotation(d.annotations, "source") is not None:
            sym.sourced.add(sid)
        if find_annotation(d.annotations, "sink") is not None:
            sym.sinked.add(sid)
        pa = find_annotation(d.annotations, "pipeline")
        if pa is not None:
            _check_pipeline_annotation(sid, d, pa, diags)
        fa = find_annotation(d.annotations, "flightRecorder")
        if fa is not None:
            _check_flight_annotation(sid, d, fa, diags)
        oe = find_annotation(d.annotations, "OnError")
        if oe is None:
            continue
        action = (oe.element("action") or oe.element(None) or "LOG").upper()
        if action not in ("LOG", "STREAM", "STORE"):
            diags.append(Diagnostic(
                "SA110",
                f"stream '{sid}': unknown @OnError action '{action}' "
                "(expected LOG, STREAM, or STORE)",
                getattr(d, "line", None), getattr(d, "col", None),
            ))
            continue
        if action == "STREAM":
            if "_error" in sym.streams[sid]:
                diags.append(Diagnostic(
                    "SA111",
                    f"stream '{sid}': @OnError(action='STREAM') reserves the "
                    "attribute name '_error'",
                    getattr(d, "line", None), getattr(d, "col", None),
                ))
            sym.fault_parents.add(sid)
            fault = dict(sym.streams[sid])
            fault["_error"] = AttrType.STRING
            sym.streams["!" + sid] = fault

    # @app:watermark(late.policy='stream'|'apply') auto-defines `!S` for
    # EVERY stream (the late/expired side channel — app_runtime mirrors
    # this), so `from !S` must resolve even without @OnError(STREAM)
    wm = find_annotation(app.annotations, "app:watermark")
    if wm is not None and (wm.element("late.policy") or "drop") in (
        "stream", "apply"
    ):
        for sid in app.stream_definitions:
            if "!" + sid in sym.streams:
                continue
            if "_error" in sym.streams[sid]:
                diags.append(Diagnostic(
                    "SA111",
                    f"stream '{sid}': @app:watermark late.policy="
                    f"'{wm.element('late.policy')}' reserves the attribute "
                    "name '_error' on every stream",
                ))
                continue
            sym.fault_parents.add(sid)
            fault = dict(sym.streams[sid])
            fault["_error"] = AttrType.STRING
            sym.streams["!" + sid] = fault

    from siddhi_tpu.core.error_store import (
        iter_definition_onerror_problems,
        resolve_definition_onerror_action,
    )

    for tid, d in app.table_definitions.items():
        sym.tables[tid] = _attrs_schema(d, diags, "table")
        oe = find_annotation(d.annotations, "OnError")
        if oe is None:
            continue
        # ONE rule set with the runtime wiring (core/error_store.py —
        # like SA126-128 ride the core/supervision.py resolvers)
        for tag, msg in iter_definition_onerror_problems(oe, "table", tid):
            diags.append(Diagnostic(
                "SA110" if tag == "action" else "SA111", msg,
                getattr(d, "line", None), getattr(d, "col", None),
            ))

    for wid, d in app.window_definitions.items():
        sym.windows[wid] = _attrs_schema(d, diags, "window")
        oe = find_annotation(d.annotations, "OnError")
        if oe is None:
            continue
        schema = sym.windows[wid] or {}
        problems = list(iter_definition_onerror_problems(
            oe, "window", wid, schema
        ))
        for tag, msg in problems:
            diags.append(Diagnostic(
                "SA110" if tag == "action" else "SA111", msg,
                getattr(d, "line", None), getattr(d, "col", None),
            ))
        if any(tag == "action" for tag, _msg in problems):
            continue
        if resolve_definition_onerror_action(oe) == "STREAM":
            sym.fault_parents.add(wid)
            fault = dict(schema)
            fault["_error"] = AttrType.STRING
            sym.streams["!" + wid] = fault

    # triggers each define a stream <id>(triggered_time long)
    # (reference: DefinitionParserHelper trigger stream registration)
    for tid in app.trigger_definitions:
        sym.streams[tid] = {"triggered_time": AttrType.LONG}
        sym.sourced.add(tid)

    for fid, fdef in app.function_definitions.items():
        sym.functions[fid] = fdef.return_type

    for aid, adef in app.aggregation_definitions.items():
        sym.aggregations[aid] = None  # bucket-view schema: leave open
        sym.aggregation_defs[aid] = adef

    _apply_selfmon_annotation(app, sym, diags)
    _apply_slo_annotation(app, sym, diags)
    _check_fuse_annotation(app, diags)
    _check_shard_annotation(app, diags)
    _check_lineage_annotation(app, diags)
    _check_wire_annotation(app, sym, diags)
    _check_watermark_annotation(app, diags)
    _check_supervision_annotations(app, diags)
    _check_blackbox_annotation(app, diags)

    return sym
