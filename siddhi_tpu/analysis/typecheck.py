"""Expression scope resolution + type inference for the semantic analyzer.

Mirrors `core/executor.py` (Scope._resolve, _arith/promote, _compare,
_require_bool, _compile_function) and `core/aggregators.py` (build_aggregator
type matrix) — but instead of compiling, it *infers* and reports diagnostics
with source locations, and it degrades gracefully: any type it cannot know
statically (extension functions, open schemas downstream of extension stream
functions) becomes `None` ("unknown") and downstream checks are skipped
rather than guessed.
"""

from __future__ import annotations

from typing import Optional

from siddhi_tpu.core.executor import AGGREGATOR_NAMES
from siddhi_tpu.core.types import NUMERIC_TYPES, AttrType, promote
from siddhi_tpu.query_api.expression import (
    Add,
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    Divide,
    Expression,
    In,
    IsNull,
    Mod,
    Multiply,
    Not,
    Or,
    Subtract,
    Variable,
)

from siddhi_tpu.analysis.diagnostics import ERROR, WARNING, Diagnostic

_TYPE_NAMES = {
    "string": AttrType.STRING,
    "int": AttrType.INT,
    "long": AttrType.LONG,
    "float": AttrType.FLOAT,
    "double": AttrType.DOUBLE,
    "bool": AttrType.BOOL,
    "object": AttrType.OBJECT,
}

_ARITH = (Add, Subtract, Multiply, Divide, Mod)


def _loc(node) -> tuple:
    return getattr(node, "line", None), getattr(node, "col", None)


class AnalysisScope:
    """Name-resolution scope chain, mirroring executor.Scope resolution order
    (qualified ref walk, prefer_parent for in-table conditions, prefer_default
    for pattern-atom filters, per-level ambiguity)."""

    def __init__(self, parent: Optional["AnalysisScope"] = None):
        self.parent = parent
        self.refs: dict[str, Optional[dict]] = {}
        self.default_ref: Optional[str] = parent.default_ref if parent else None
        self.prefer_default = False
        self.prefer_parent = False

    def add(self, ref: str, schema: Optional[dict]) -> "AnalysisScope":
        self.refs[ref] = schema
        if self.default_ref is None:
            self.default_ref = ref
        return self

    def child(self) -> "AnalysisScope":
        return AnalysisScope(self)

    def has_open_ref(self) -> bool:
        scope: Optional[AnalysisScope] = self
        while scope is not None:
            if any(s is None for s in scope.refs.values()):
                return True
            scope = scope.parent
        return False

    def lookup_ref(self, ref: str) -> tuple[bool, Optional[dict]]:
        scope: Optional[AnalysisScope] = self
        while scope is not None:
            if ref in scope.refs:
                return True, scope.refs[ref]
            scope = scope.parent
        return False, None

    def all_refs(self) -> list[str]:
        out: list[str] = []
        scope: Optional[AnalysisScope] = self
        while scope is not None:
            out.extend(r for r in scope.refs if r not in out)
            scope = scope.parent
        return out


class ExprChecker:
    """Stateful walker: `infer(expr, scope)` returns the expression's
    AttrType (or None = unknown) and appends diagnostics."""

    def __init__(
        self,
        symbols,
        diags: list[Diagnostic],
        query: Optional[str] = None,
        allow_aggregators: bool = False,
    ):
        self.sym = symbols
        self.diags = diags
        self.query = query
        self.allow_aggregators = allow_aggregators

    def diag(self, code: str, message: str, node=None, severity: str = ERROR) -> None:
        line, col = _loc(node) if node is not None else (None, None)
        self.diags.append(
            Diagnostic(code, message, line, col, severity, self.query)
        )

    # ---- variables -------------------------------------------------------

    def resolve_variable(self, var: Variable, scope: AnalysisScope) -> Optional[AttrType]:
        if var.stream_id is not None:
            found, schema = scope.lookup_ref(var.stream_id)
            if not found:
                self.diag(
                    "SA102",
                    f"unknown stream reference '{var.stream_id}' "
                    f"(in scope: {', '.join(sorted(scope.all_refs())) or 'none'})",
                    var,
                )
                return None
            if schema is None:
                return None  # open schema: attributes unknown
            if var.attribute == "":
                return None  # bare stream ref (`e1[0] is null` form)
            if var.attribute not in schema:
                self.diag(
                    "SA103",
                    f"'{var.stream_id}' has no attribute '{var.attribute}' "
                    f"(has: {', '.join(schema) or 'none'})",
                    var,
                )
                return None
            return schema[var.attribute]

        # unqualified attribute
        if scope.prefer_parent and scope.parent is not None:
            t = self._try_resolve_silent(var, scope.parent)
            if t is not _MISS:
                return t
        if scope.prefer_default and scope.default_ref is not None:
            s: Optional[AnalysisScope] = scope
            while s is not None:
                schema = s.refs.get(scope.default_ref)
                if schema is None and scope.default_ref in s.refs:
                    return None  # open default ref
                if schema is not None and var.attribute in schema:
                    return schema[var.attribute]
                s = s.parent
        s = scope
        while s is not None:
            if any(sc is None for sc in s.refs.values()):
                return None  # an open ref at this level could hold the attr
            hits = [
                (ref, schema[var.attribute])
                for ref, schema in s.refs.items()
                if var.attribute in schema
            ]
            if len(hits) > 1:
                types = {t for _, t in hits}
                self.diag(
                    "SA104",
                    f"unqualified attribute '{var.attribute}' is ambiguous "
                    f"across {sorted(r for r, _ in hits)} — qualify it",
                    var,
                    severity=WARNING,
                )
                return hits[0][1] if len(types) == 1 else None
            if hits:
                return hits[0][1]
            s = s.parent
        self.diag(
            "SA103",
            f"unknown attribute '{var.attribute}' "
            f"(in scope: {', '.join(sorted(scope.all_refs())) or 'none'})",
            var,
        )
        return None

    def _try_resolve_silent(self, var: Variable, scope: AnalysisScope):
        """prefer_parent probe: resolve without emitting diagnostics."""
        if scope.has_open_ref():
            return None
        s: Optional[AnalysisScope] = scope
        while s is not None:
            hits = [schema[var.attribute] for schema in s.refs.values()
                    if schema is not None and var.attribute in schema]
            if hits:
                return hits[0]
            s = s.parent
        return _MISS

    # ---- expressions -----------------------------------------------------

    def infer(self, expr: Expression, scope: AnalysisScope) -> Optional[AttrType]:
        if isinstance(expr, Constant):
            return expr.type

        if isinstance(expr, Variable):
            return self.resolve_variable(expr, scope)

        if isinstance(expr, _ARITH):
            lt = self.infer(expr.left, scope)
            rt = self.infer(expr.right, scope)
            op = {Add: "+", Subtract: "-", Multiply: "*", Divide: "/", Mod: "%"}[
                type(expr)
            ]
            for side, t in (("left", lt), ("right", rt)):
                if t is not None and t not in NUMERIC_TYPES:
                    self.diag(
                        "SA202",
                        f"arithmetic '{op}' on non-numeric {side} operand ({t!r})",
                        expr,
                    )
                    return None
            if lt is None or rt is None:
                return None
            return promote(lt, rt)

        if isinstance(expr, Compare):
            lt = self.infer(expr.left, scope)
            rt = self.infer(expr.right, scope)
            if lt is None or rt is None:
                return AttrType.BOOL
            if lt in NUMERIC_TYPES and rt in NUMERIC_TYPES:
                return AttrType.BOOL
            if lt == rt and lt in (AttrType.BOOL, AttrType.STRING, AttrType.OBJECT):
                if expr.op not in (CompareOp.EQ, CompareOp.NEQ):
                    self.diag(
                        "SA201",
                        f"operator '{expr.op.value}' is not defined for {lt!r}",
                        expr,
                    )
                return AttrType.BOOL
            self.diag(
                "SA201",
                f"cannot compare {lt!r} {expr.op.value} {rt!r}",
                expr,
            )
            return AttrType.BOOL

        if isinstance(expr, (And, Or)):
            word = "and" if isinstance(expr, And) else "or"
            for side in (expr.left, expr.right):
                t = self.infer(side, scope)
                if t is not None and t is not AttrType.BOOL:
                    self.diag(
                        "SA204",
                        f"'{word}' operand must be BOOL, got {t!r}",
                        side,
                    )
            return AttrType.BOOL

        if isinstance(expr, Not):
            t = self.infer(expr.expression, scope)
            if t is not None and t is not AttrType.BOOL:
                self.diag("SA204", f"'not' operand must be BOOL, got {t!r}", expr)
            return AttrType.BOOL

        if isinstance(expr, IsNull):
            if expr.expression is not None:
                # bare `name is null` keeps both readings (attribute vs pattern
                # state alias): if the name matches an in-scope ref, the
                # compile layer prefers the state-alias reading — do the same
                if expr.stream_id is not None:
                    found, _schema = scope.lookup_ref(expr.stream_id)
                    if found:
                        return AttrType.BOOL
                self.infer(expr.expression, scope)
                return AttrType.BOOL
            if expr.stream_id is not None:
                found, _schema = scope.lookup_ref(expr.stream_id)
                if not found:
                    self.diag(
                        "SA102",
                        f"unknown stream reference '{expr.stream_id}' in 'is null'",
                        expr,
                    )
            return AttrType.BOOL

        if isinstance(expr, In):
            self._check_in_table(expr, scope)
            return AttrType.BOOL

        if isinstance(expr, AttributeFunction):
            return self.infer_function(expr, scope)

        return None  # unknown node kind: stay permissive

    def _check_in_table(self, expr: In, scope: AnalysisScope) -> None:
        table = self.sym.tables.get(expr.source_id)
        if table is None:
            # aggregation duration tables ("<agg>_SECONDS"...) register as
            # tables at runtime; treat them as open schemas
            if any(
                expr.source_id.startswith(aid + "_")
                for aid in self.sym.aggregations
            ):
                table_schema: Optional[dict] = None
            elif expr.source_id in self.sym.windows:
                table_schema = self.sym.windows[expr.source_id]
            else:
                self.diag(
                    "SA108",
                    f"'in {expr.source_id}': no such table "
                    f"(tables: {', '.join(sorted(self.sym.tables)) or 'none'})",
                    expr,
                )
                return
        else:
            table_schema = table
        inner = scope.child()
        inner.add(expr.source_id, table_schema)
        inner.prefer_parent = True
        t = self.infer(expr.expression, inner)
        if t is not None and t is not AttrType.BOOL:
            self.diag("SA203", f"in-table condition must be BOOL, got {t!r}", expr)

    # ---- functions & aggregators ----------------------------------------

    def is_aggregator(self, expr: Expression) -> bool:
        return (
            isinstance(expr, AttributeFunction)
            and expr.namespace is None
            and expr.name in AGGREGATOR_NAMES
        )

    def infer_no_agg(self, expr: Expression, scope: AnalysisScope) -> Optional[AttrType]:
        """Infer with aggregators disallowed (aggregator arguments — nested
        aggregators are rejected by the executor after lifting)."""
        prev = self.allow_aggregators
        self.allow_aggregators = False
        try:
            return self.infer(expr, scope)
        finally:
            self.allow_aggregators = prev

    def infer_function(
        self, expr: AttributeFunction, scope: AnalysisScope
    ) -> Optional[AttrType]:
        if self.is_aggregator(expr):
            if not self.allow_aggregators:
                self.diag(
                    "SA209",
                    f"aggregator '{expr.name}' is only valid in a select "
                    "clause (or having)",
                    expr,
                )
                return None
            return self.infer_aggregator(expr, scope)

        name = f"{expr.namespace}:{expr.name}" if expr.namespace else expr.name
        params = expr.parameters
        sub = self  # scalar args inherit the aggregator policy (lifting)

        if name in ("cast", "convert"):
            return sub._cast_type(expr, scope)
        if name == "coalesce":
            types = [sub.infer(p, scope) for p in params]
            if not params:
                self.diag("SA207", f"{name}() needs at least one argument", expr)
                return None
            known = [t for t in types if t is not None]
            if known and any(t != known[0] for t in known):
                self.diag(
                    "SA207",
                    f"coalesce requires homogeneous parameter types, got "
                    f"{[t for t in types]!r}",
                    expr,
                )
                return None
            return types[0]
        if name == "ifThenElse":
            if len(params) != 3:
                self.diag(
                    "SA207",
                    f"ifThenElse(condition, then, else) takes 3 arguments, "
                    f"got {len(params)}",
                    expr,
                )
                for p in params:
                    sub.infer(p, scope)
                return None
            ct = sub.infer(params[0], scope)
            if ct is not None and ct is not AttrType.BOOL:
                self.diag(
                    "SA207",
                    f"ifThenElse condition must be BOOL, got {ct!r}",
                    params[0],
                )
            at, bt = sub.infer(params[1], scope), sub.infer(params[2], scope)
            if at is None or bt is None:
                return None
            if at in NUMERIC_TYPES and bt in NUMERIC_TYPES:
                return promote(at, bt)
            if at == bt:
                return at
            self.diag(
                "SA207", f"ifThenElse branches {at!r} vs {bt!r}", expr
            )
            return None
        if name.startswith("instanceOf") and expr.namespace is None:
            target = _TYPE_NAMES.get(name[len("instanceOf"):].lower())
            if target is None:
                self.diag("SA208", f"unknown function '{name}'", expr)
                return None
            if len(params) != 1:
                self.diag(
                    "SA207", f"{name}(value) takes 1 argument, got {len(params)}",
                    expr,
                )
            for p in params:
                sub.infer(p, scope)
            return AttrType.BOOL
        if name in ("maximum", "minimum"):
            if not params:
                self.diag("SA207", f"{name}() needs at least one argument", expr)
                return None
            types = [sub.infer(p, scope) for p in params]
            out: Optional[AttrType] = None
            for p, t in zip(params, types):
                if t is not None and t not in NUMERIC_TYPES:
                    self.diag(
                        "SA207",
                        f"{name} arguments must be numeric, got {t!r}",
                        p,
                    )
                    return None
            if any(t is None for t in types):
                return None
            out = types[0]
            for t in types[1:]:
                out = promote(out, t)
            return out
        if name == "eventTimestamp":
            return AttrType.LONG
        if name == "currentTimeMillis":
            return AttrType.LONG
        if name == "UUID":
            return AttrType.STRING
        if name == "default":
            if len(params) != 2:
                self.diag(
                    "SA207",
                    f"default(value, fallback) takes 2 arguments, got {len(params)}",
                    expr,
                )
                for p in params:
                    sub.infer(p, scope)
                return None
            st, dt = sub.infer(params[0], scope), sub.infer(params[1], scope)
            if st is None or dt is None:
                return st
            if st != dt and not (st in NUMERIC_TYPES and dt in NUMERIC_TYPES):
                self.diag(
                    "SA207", f"default({st!r}, {dt!r}) type mismatch", expr
                )
            return st

        # script-defined functions (`define function f[...] return T {...}`)
        for p in params:
            sub.infer(p, scope)
        if expr.namespace is None and expr.name in self.sym.functions:
            return self.sym.functions[expr.name]

        from siddhi_tpu.core.extension import lookup_function

        if lookup_function(name) is not None:
            return None  # extension: return type unknowable statically
        self.diag("SA208", f"unknown function '{name}'", expr)
        return None

    def _cast_type(self, expr: AttributeFunction, scope: AnalysisScope) -> Optional[AttrType]:
        name = expr.name
        params = expr.parameters
        if len(params) != 2 or not isinstance(params[1], Constant):
            self.diag(
                "SA207",
                f"{name}(value, 'type') requires a value and a constant type name",
                expr,
            )
            for p in params:
                self.infer(p, scope)
            return None
        target = _TYPE_NAMES.get(str(params[1].value).lower())
        if target is None:
            self.diag(
                "SA207", f"unknown {name} target {params[1].value!r}", params[1]
            )
            self.infer(params[0], scope)
            return None
        src = self.infer(params[0], scope)
        if src is None:
            return target
        # mirror executor._compile_function cast/convert legality matrix
        if target in (AttrType.STRING, AttrType.OBJECT) or src in (
            AttrType.STRING,
            AttrType.OBJECT,
        ):
            if src == target:
                return target
            if target is AttrType.STRING and src in NUMERIC_TYPES:
                return target
            self.diag(
                "SA207",
                f"cannot {name} {src!r} to {target!r} "
                "(string parsing/printing beyond numeric->string is not "
                "supported on device)",
                expr,
            )
            return target
        if target is AttrType.BOOL or src is AttrType.BOOL:
            if src != target:
                self.diag("SA207", f"cannot {name} {src!r} to {target!r}", expr)
            return target
        return target

    def infer_aggregator(
        self, expr: AttributeFunction, scope: AnalysisScope
    ) -> Optional[AttrType]:
        low = expr.name.lower()
        arg_types = [self.infer_no_agg(p, scope) for p in expr.parameters]
        if low == "count":
            return AttrType.LONG
        if not expr.parameters:
            self.diag(
                "SA305", f"aggregator '{expr.name}' needs an argument", expr
            )
            return None
        arg_t = arg_types[0]
        if low == "distinctcount":
            return AttrType.LONG
        if arg_t is not None and arg_t not in NUMERIC_TYPES:
            self.diag(
                "SA305",
                f"aggregator '{expr.name}' needs a numeric argument, got {arg_t!r}",
                expr.parameters[0],
            )
            return None
        if low == "sum":
            if arg_t is None:
                return None
            return (
                AttrType.LONG
                if arg_t in (AttrType.INT, AttrType.LONG)
                else AttrType.DOUBLE
            )
        if low in ("avg", "stddev"):
            return AttrType.DOUBLE
        if low in ("min", "max", "minforever", "maxforever"):
            return arg_t
        return None


_MISS = object()
