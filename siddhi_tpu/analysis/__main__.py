"""SiddhiQL linter CLI.

    python -m siddhi_tpu.analysis app.siddhi [more.siddhi ...]
        [--format=text|json] [--werror] [--codes] [--explain] [--plan]

Exit codes: 0 clean, 1 semantic errors (or warnings under --werror),
2 unreadable/unparsable input. Parse errors are reported as SA001 with the
parser's line/column rather than a traceback.

`--explain` renders the app's dataflow plan (the EXPLAIN half of the
runtime's EXPLAIN ANALYZE — same graph, no live counters; see
observability/explain.py) instead of diagnostics. Combine with
`--format=json` for the raw node/edge plan.

`--plan` emits the static FusionPlan (analysis/fusion.py): per-stream
fusable query groups, shared-state candidates, fusion blockers, and the
per-query cost model (state bytes, predicted compile counts, selectivity
estimates). Never fails on semantically-bad apps (rc 0; rc 2 only for
unparsable input) — the plan is best-effort by contract, like --explain.
"""

from __future__ import annotations

import argparse
import sys

from siddhi_tpu.analysis import CODES
from siddhi_tpu.analysis.diagnostics import AnalysisResult, Diagnostic, ERROR
from siddhi_tpu.core.errors import SiddhiParserError


def _lint_source(source: str) -> AnalysisResult:
    from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

    try:
        app = SiddhiCompiler.parse(source)
    except SiddhiParserError as exc:
        return AnalysisResult([
            Diagnostic(
                "SA001", str(exc),
                getattr(exc, "line", None), getattr(exc, "col", None),
                ERROR,
            )
        ])
    from siddhi_tpu.analysis.analyzer import analyze as analyze_app

    return analyze_app(app)


def _explain_source(source: str, name: str, fmt: str) -> int:
    """`--explain`: render the static dataflow plan; rc 2 on parse errors."""
    import json

    from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

    try:
        app = SiddhiCompiler.parse(source)
    except SiddhiParserError as exc:
        print(f"{name}: SA001: {exc}", file=sys.stderr)
        return 2
    from siddhi_tpu.observability.explain import explain_static

    if fmt == "json":
        print(json.dumps(explain_static(app, fmt="dict"), default=str))
    else:
        print(explain_static(app))
    return 0


def _plan_source(source: str, name: str, fmt: str) -> int:
    """`--plan`: emit the static FusionPlan; rc 2 on parse errors."""
    from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

    try:
        app = SiddhiCompiler.parse(source)
    except SiddhiParserError as exc:
        print(f"{name}: SA001: {exc}", file=sys.stderr)
        return 2
    from siddhi_tpu.analysis.fusion import build_fusion_plan, render_plan_text

    plan = build_fusion_plan(app)
    if fmt == "json":
        print(plan.to_json())
    else:
        print(render_plan_text(plan))
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m siddhi_tpu.analysis",
        description="Compile-time semantic analyzer / linter for SiddhiQL apps.",
    )
    ap.add_argument("files", nargs="*", help="SiddhiQL app files ('-' = stdin)")
    ap.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="diagnostic output format (default: text)",
    )
    ap.add_argument(
        "--werror", action="store_true",
        help="treat warnings as errors (non-zero exit on any diagnostic)",
    )
    ap.add_argument(
        "--codes", action="store_true",
        help="print the SA### diagnostic catalog and exit",
    )
    ap.add_argument(
        "--explain", action="store_true",
        help="render the app's dataflow plan (static EXPLAIN) instead of "
        "diagnostics",
    )
    ap.add_argument(
        "--plan", action="store_true",
        help="emit the static FusionPlan (fusable groups, shared-state "
        "candidates, per-query cost model) instead of diagnostics",
    )
    args = ap.parse_args(argv)

    if args.codes:
        for code, desc in sorted(CODES.items()):
            print(f"{code}  {desc}")
        return 0
    if not args.files:
        ap.error("no input files (or use --codes)")

    worst = 0
    for path in args.files:
        try:
            source = (
                sys.stdin.read() if path == "-" else open(path).read()
            )
        except OSError as exc:
            print(f"{path}: cannot read: {exc}", file=sys.stderr)
            worst = max(worst, 2)
            continue
        name = "<stdin>" if path == "-" else path
        if args.explain:
            worst = max(worst, _explain_source(source, name, args.format))
            continue
        if args.plan:
            worst = max(worst, _plan_source(source, name, args.format))
            continue
        result = _lint_source(source)
        if args.format == "json":
            print(result.to_json(name))
        else:
            print(result.format(name))
        if any(d.code == "SA001" for d in result.diagnostics):
            worst = max(worst, 2)
        elif result.errors or (args.werror and result.warnings):
            worst = max(worst, 1)
    return worst


if __name__ == "__main__":
    sys.exit(main())
