"""Diagnostics model for the compile-time semantic analyzer.

Every finding carries a stable `SA###` code (documented in the README and in
`CODES` below), a severity, and — when the analyzed app came out of the
SiddhiQL parser — the 1-based line/column of the offending token, threaded
from the tokenizer through the query-api AST (`SourceLocated`).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

from siddhi_tpu.core.errors import SiddhiAppCreationError

ERROR = "error"
WARNING = "warning"

# Stable diagnostic catalog. Codes are append-only: never renumber.
CODES: dict[str, str] = {
    "SA000": "internal analyzer error (analysis incomplete, not an app defect)",
    "SA001": "SiddhiQL syntax error (reported by the CLI for unparsable apps)",
    # name resolution
    "SA101": "undefined stream / window / input source",
    "SA102": "unknown stream reference in an expression",
    "SA103": "unknown attribute",
    "SA104": "ambiguous unqualified attribute (warning)",
    "SA105": "duplicate query name",
    "SA106": "fault stream '!S' consumed but 'S' does not declare @OnError(action='STREAM')",
    "SA107": "insert into fault stream '!S' but 'S' does not declare @OnError(action='STREAM')",
    "SA108": "unknown table",
    "SA109": "duplicate attribute name in a definition",
    "SA110": "invalid @OnError action",
    "SA111": "reserved attribute name",
    "SA112": "invalid @pipeline annotation (unknown key / bad depth / bad disable)",
    "SA113": "invalid @app:selfmon annotation (bad interval / unknown key / reserved stream name)",
    "SA114": "invalid @flightRecorder annotation (bad size / unknown key)",
    "SA115": "invalid partition key (OBJECT-typed key expression, or a "
             "partitioned query consumes a stream the partition declares "
             "no key for)",
    "SA116": "aggregation 'aggregate by' attribute must be INT/LONG",
    "SA117": "invalid 'within'/'per' clause (aggregation joins and store "
             "queries; warning when the clause is silently ignored)",
    "SA118": "malformed store query (no from-store and no write output)",
    # cost model / fusion planner (warnings)
    "SA120": "unbounded pattern state: 'every' with no 'within' bound "
             "(token-table growth; warning)",
    "SA121": "unbounded or oversized window/aggregation state (no expiry, "
             "or state beyond the device budget; warning)",
    "SA122": "statically-predicted recompile churn (tail-variant ladder, "
             "re-published batch shapes; warning)",
    "SA123": "identical window state duplicated across queries of one "
             "stream (shareable; warning)",
    "SA124": "fusion blocker: the named hazard excludes this query from "
             "its stream's fusable group (warning)",
    "SA125": "invalid @app:fuse annotation (unknown option or bad "
             "disable value)",
    "SA126": "invalid @app:persist annotation (bad interval / bad keep / "
             "unknown key)",
    "SA127": "invalid @app:restart annotation (unknown policy / bad "
             "max.attempts / bad backoff)",
    "SA128": "invalid @app:admission annotation (unknown policy / bad "
             "rate.limit or max.pending / no bound declared)",
    "SA129": "invalid @app:shard annotation (devices out of range / "
             "unknown axis / unknown option)",
    "SA130": "hot add_query candidate conflicts with the live app "
             "(missing @info name / duplicate query id / undeclared stream)",
    "SA131": "invalid @app:lineage annotation (bad capacity / unknown mode "
             "/ bad sample.every / unknown option)",
    "SA132": "invalid @app:wire annotation (unknown option / bad range "
             "'lo..hi' / bad dict capacity / bad delta dtype / unknown "
             "stream or column / encoder-type mismatch)",
    "SA133": "h2d-dominant wide column: a declared column's type forces a "
             "wide wire encoding that dominates the stream's h2d "
             "bytes/event — declare an int/long range (or dict/delta) via "
             "@app:wire, or use interned strings (warning)",
    "SA134": "invalid @app:watermark annotation (missing/bad bound / bad "
             "idle.timeout / unknown late.policy / allowed.lateness "
             "without late.policy='apply' / unknown option)",
    # value analysis (analysis/values.py; warnings)
    "SA135": "provably-false filter: on the proven value domain the "
             "predicate can never hold, so the query is unreachable "
             "(warning)",
    "SA136": "comparison that can never vary: the proven value domain "
             "decides it always-true or always-false (warning)",
    "SA137": "arithmetic hazard on a proven domain: possible overflow of "
             "the result type, or division/modulo by a domain containing "
             "zero (warning)",
    "SA138": "inferred-encodable wide column: the dominant wide column's "
             "bounds/cardinality/monotonicity are PROVEN by value "
             "analysis, so wire inference compacts it with no annotation "
             "(informational successor to SA133; warning)",
    "SA139": "malformed @app:slo annotation: unknown option, invalid "
             "objective/window/burn threshold, no objective at all, or a "
             "user definition of the reserved SloAlertStream",
    "SA140": "invalid @app:blackbox annotation (bad window / unknown "
             "trigger / bad keep or ring / bad checkpoint.interval or "
             "debounce / unknown option)",
    # typing
    "SA201": "incompatible comparison operand types",
    "SA202": "arithmetic on a non-numeric operand",
    "SA203": "condition is not boolean (filter / having / on / range partition)",
    "SA204": "logical operator on a non-boolean operand",
    "SA205": "insert-into arity mismatch against the target schema",
    "SA206": "insert-into attribute type mismatch against the target schema",
    "SA207": "scalar function argument error",
    "SA208": "unknown function",
    "SA209": "aggregator used outside select / having",
    "SA210": "expression projection needs an 'as' name",
    "SA211": "duplicate output attribute name",
    "SA212": "order by on a STRING/OBJECT attribute",
    # windows / stream functions / aggregators
    "SA301": "unknown window type",
    "SA302": "window or stream-function argument error",
    "SA303": "unknown stream function",
    "SA305": "aggregator argument error",
    # dataflow (warnings)
    "SA401": "dead stream: defined but never produced or consumed (warning)",
    "SA402": "named window consumed but never fed by any query (warning)",
    "SA403": "stream dataflow cycle (warning)",
}


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    code: str
    message: str
    line: Optional[int] = None
    col: Optional[int] = None
    severity: str = ERROR
    query: Optional[str] = None  # query id context, when inside a query

    def format(self, source_name: str = "<app>") -> str:
        loc = f"{source_name}"
        if self.line is not None:
            loc += f":{self.line}:{self.col if self.col is not None else 0}"
        ctx = f" [in {self.query}]" if self.query else ""
        return f"{loc}: {self.severity}: {self.code}: {self.message}{ctx}"

    def to_dict(self) -> dict:
        return {
            "code": self.code,
            "severity": self.severity,
            "message": self.message,
            "line": self.line,
            "col": self.col,
            "query": self.query,
        }


@dataclasses.dataclass
class AnalysisResult:
    diagnostics: list[Diagnostic] = dataclasses.field(default_factory=list)
    app_name: str = "SiddhiApp"
    # static FusionPlan (analysis/fusion.py) built by the same pass; None
    # when the pass was skipped or the analyzer degraded (SA000)
    fusion_plan: object = None

    @property
    def errors(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == ERROR]

    @property
    def warnings(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == WARNING]

    @property
    def ok(self) -> bool:
        return not self.errors

    def format(self, source_name: str = "<app>") -> str:
        lines = [d.format(source_name) for d in self.diagnostics]
        lines.append(
            f"{len(self.errors)} error(s), {len(self.warnings)} warning(s)"
        )
        return "\n".join(lines)

    def to_json(self, source_name: str = "<app>") -> str:
        return json.dumps(
            {
                "app": self.app_name,
                "source": source_name,
                "diagnostics": [d.to_dict() for d in self.diagnostics],
                "errors": len(self.errors),
                "warnings": len(self.warnings),
            },
            indent=2,
        )

    def raise_if_errors(self, source_name: str = "<app>") -> "AnalysisResult":
        if self.errors:
            raise SiddhiAnalysisError(self, source_name)
        return self


class SiddhiAnalysisError(SiddhiAppCreationError):
    """Aggregated semantic errors from `analyze()` (strict mode): one raise
    listing every error diagnostic, instead of dying on the first."""

    def __init__(self, result: AnalysisResult, source_name: str = "<app>"):
        self.result = result
        self.diagnostics = result.errors
        msgs = "\n".join("  " + d.format(source_name) for d in result.errors)
        super().__init__(
            f"semantic analysis of '{result.app_name}' found "
            f"{len(result.errors)} error(s):\n{msgs}"
        )
