"""Native host components: the C++ ingress ring with ctypes bindings.

The ring (ring.cpp) is the native analog of the reference's LMAX Disruptor
substrate (StreamJunction.java:262-298): a lock-free bounded MPSC queue of
fixed-width numeric rows, drained by one consumer into columnar batches. It
compiles on first use with the system toolchain; environments without g++
fall back to the pure-Python queue path transparently.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False


def _build_dir() -> str:
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
    os.makedirs(d, exist_ok=True)
    return d


def load_ring_library() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the ring library; None when no toolchain."""
    global _LIB, _LIB_FAILED
    with _LIB_LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        src = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ring.cpp")
        out = os.path.join(_build_dir(), "libsiddhi_ring.so")
        try:
            if not os.path.exists(out) or os.path.getmtime(out) < os.path.getmtime(src):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", out, src],
                    check=True, capture_output=True,
                )
            lib = ctypes.CDLL(out)
        except Exception:
            _LIB_FAILED = True
            return None
        lib.ring_create.restype = ctypes.c_void_p
        lib.ring_create.argtypes = [ctypes.c_size_t, ctypes.c_size_t]
        lib.ring_destroy.argtypes = [ctypes.c_void_p]
        lib.ring_push.restype = ctypes.c_int
        lib.ring_push.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.POINTER(ctypes.c_double),
        ]
        lib.ring_pop_batch.restype = ctypes.c_size_t
        lib.ring_pop_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_size_t,
        ]
        lib.ring_size.restype = ctypes.c_size_t
        lib.ring_size.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


class NativeIngressRing:
    """Python handle over the C++ MPSC ring; one consumer thread drains
    row-major double payloads into per-column numpy arrays."""

    def __init__(self, capacity: int, width: int):
        lib = load_ring_library()
        if lib is None:
            raise RuntimeError("native ring unavailable (no C++ toolchain)")
        self._lib = lib
        self.width = int(width)
        self._ptr = lib.ring_create(int(capacity), self.width)
        if not self._ptr:
            raise MemoryError("ring_create failed")
        # reusable drain buffers
        self._ts_buf = np.empty((0,), dtype=np.int64)
        self._row_buf = np.empty((0,), dtype=np.float64)

    def push(self, ts: int, row) -> bool:
        arr = np.asarray(row, dtype=np.float64)
        return bool(
            self._lib.ring_push(
                self._ptr, int(ts),
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            )
        )

    def push_many(self, timestamps, rows) -> int:
        """Blocking bulk push (spins on back-pressure); returns count."""
        n = 0
        for ts, row in zip(timestamps, rows):
            while not self.push(ts, row):
                pass  # ring full: busy-wait back-pressure like Disruptor
            n += 1
        return n

    def pop_batch(self, max_rows: int):
        """-> (ts [n] int64, rows [n, width] float64)."""
        if self._ts_buf.shape[0] < max_rows:
            self._ts_buf = np.empty((max_rows,), dtype=np.int64)
            self._row_buf = np.empty((max_rows * self.width,), dtype=np.float64)
        n = self._lib.ring_pop_batch(
            self._ptr,
            self._ts_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
            self._row_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            int(max_rows),
        )
        n = int(n)
        return (
            self._ts_buf[:n].copy(),
            self._row_buf[: n * self.width].reshape(n, self.width).copy(),
        )

    def size(self) -> int:
        return int(self._lib.ring_size(self._ptr))

    def close(self) -> None:
        if self._ptr:
            self._lib.ring_destroy(self._ptr)
            self._ptr = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
