// Lock-free bounded MPSC ingress ring (Vyukov bounded-queue scheme).
//
// Reference analog: the LMAX Disruptor ring buffer behind @async streams
// (modules/siddhi-core/.../core/stream/StreamJunction.java:262-298, the
// engine's performance-critical substrate per SURVEY.md). Here the ring is
// the native host-side stage in front of device micro-batching: producers
// (any thread, no GIL needed) publish fixed-width numeric rows; one consumer
// drains up to batch_max rows at a time straight into columnar buffers for
// EventBatch packing.
//
// Each slot: [seq][ts][v0..v_{k-1}] — values are doubles (numeric attrs and
// pre-interned string ids; integers are exact to 2^53).
//
// Build: g++ -O2 -shared -fPIC -o libsiddhi_ring.so ring.cpp  (see build.py)

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>

namespace {

struct Slot {
    std::atomic<size_t> seq;
    long long ts;
    // payload doubles follow the struct in the arena
};

struct Ring {
    size_t capacity;      // power of two
    size_t mask;
    size_t slot_doubles;  // payload width
    size_t slot_stride;   // bytes per slot incl. payload
    char* arena;
    std::atomic<size_t> tail;  // producers claim here
    std::atomic<size_t> head;  // single consumer
    std::atomic<long long> dropped;
};

inline Slot* slot_at(Ring* r, size_t i) {
    return reinterpret_cast<Slot*>(r->arena + (i & r->mask) * r->slot_stride);
}

inline double* payload(Slot* s) {
    return reinterpret_cast<double*>(reinterpret_cast<char*>(s) + sizeof(Slot));
}

size_t next_pow2(size_t v) {
    size_t p = 1;
    while (p < v) p <<= 1;
    return p;
}

}  // namespace

extern "C" {

Ring* ring_create(size_t capacity, size_t slot_doubles) {
    Ring* r = new (std::nothrow) Ring();
    if (!r) return nullptr;
    r->capacity = next_pow2(capacity < 2 ? 2 : capacity);
    r->mask = r->capacity - 1;
    r->slot_doubles = slot_doubles;
    r->slot_stride = sizeof(Slot) + slot_doubles * sizeof(double);
    // align stride to 64 bytes to keep slots off shared cache lines
    r->slot_stride = (r->slot_stride + 63) & ~size_t(63);
    r->arena = static_cast<char*>(std::calloc(r->capacity, r->slot_stride));
    if (!r->arena) {
        delete r;
        return nullptr;
    }
    for (size_t i = 0; i < r->capacity; i++) {
        slot_at(r, i)->seq.store(i, std::memory_order_relaxed);
    }
    r->tail.store(0, std::memory_order_relaxed);
    r->head.store(0, std::memory_order_relaxed);
    r->dropped.store(0, std::memory_order_relaxed);
    return r;
}

void ring_destroy(Ring* r) {
    if (!r) return;
    std::free(r->arena);
    delete r;
}

// Returns 1 on success, 0 when the ring is full (caller may retry = back-pressure).
int ring_push(Ring* r, long long ts, const double* row) {
    size_t pos = r->tail.load(std::memory_order_relaxed);
    for (;;) {
        Slot* s = slot_at(r, pos);
        size_t seq = s->seq.load(std::memory_order_acquire);
        intptr_t dif = (intptr_t)seq - (intptr_t)pos;
        if (dif == 0) {
            if (r->tail.compare_exchange_weak(pos, pos + 1,
                                              std::memory_order_relaxed)) {
                s->ts = ts;
                std::memcpy(payload(s), row, r->slot_doubles * sizeof(double));
                s->seq.store(pos + 1, std::memory_order_release);
                return 1;
            }
        } else if (dif < 0) {
            return 0;  // full
        } else {
            pos = r->tail.load(std::memory_order_relaxed);
        }
    }
}

// Single consumer: drain up to `max` rows; returns the count.
// out_ts: [max] int64; out_rows: [max * slot_doubles] doubles, row-major.
size_t ring_pop_batch(Ring* r, long long* out_ts, double* out_rows, size_t max) {
    size_t n = 0;
    size_t pos = r->head.load(std::memory_order_relaxed);
    while (n < max) {
        Slot* s = slot_at(r, pos);
        size_t seq = s->seq.load(std::memory_order_acquire);
        if ((intptr_t)seq - (intptr_t)(pos + 1) < 0) break;  // empty
        out_ts[n] = s->ts;
        std::memcpy(out_rows + n * r->slot_doubles, payload(s),
                    r->slot_doubles * sizeof(double));
        s->seq.store(pos + r->capacity, std::memory_order_release);
        pos++;
        n++;
    }
    r->head.store(pos, std::memory_order_relaxed);
    return n;
}

size_t ring_size(Ring* r) {
    return r->tail.load(std::memory_order_relaxed) -
           r->head.load(std::memory_order_relaxed);
}

size_t ring_capacity(Ring* r) { return r->capacity; }

}  // extern "C"
