"""REST deploy microservice.

Reference: modules/siddhi-service — MSF4J endpoints
`POST /siddhi/artifact/deploy` (body = SiddhiQL text) and
`GET /siddhi/artifact/undeploy/{appName}`
(src/gen/.../api/SiddhiApi.java:31-63, impl/SiddhiApiServiceImpl.java:54-110),
holding one SiddhiManager. Here: a stdlib ThreadingHTTPServer wrapper.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class SiddhiService:
    def __init__(self, host: str = "127.0.0.1", port: int = 0, manager=None):
        from siddhi_tpu import SiddhiManager

        self.manager = manager or SiddhiManager()
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _reply(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path.rstrip("/") != "/siddhi/artifact/deploy":
                    self._reply(404, {"error": "not found"})
                    return
                n = int(self.headers.get("Content-Length", "0"))
                source = self.rfile.read(n).decode()
                try:
                    rt = service.manager.create_siddhi_app_runtime(source)
                    rt.start()
                    self._reply(
                        200,
                        {"status": "deployed", "appName": rt.name},
                    )
                except Exception as e:
                    self._reply(400, {"error": str(e)})

            def do_GET(self):
                prefix = "/siddhi/artifact/undeploy/"
                if not self.path.startswith(prefix):
                    self._reply(404, {"error": "not found"})
                    return
                app_name = self.path[len(prefix):].strip("/")
                if not service.manager.shutdown_siddhi_app_runtime(app_name):
                    self._reply(404, {"error": f"no app '{app_name}'"})
                    return
                self._reply(200, {"status": "undeployed", "appName": app_name})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self.manager.shutdown()
