"""Keyed (group-by) batch reductions and the device group-slot assignment.

The reference keeps one aggregator-state object per group key in a HashMap,
looked up per event by a generated string key
(reference: query/selector/GroupByKeyGenerator.java,
query/selector/attribute/processor/executor/GroupByAggregationAttributeExecutor.java).
TPU-shaped equivalent: group state is a fixed-capacity `[G]` array indexed by a
slot; slot assignment is a vectorized probe of a persistent int64 key table —
no scan, no host round-trip — and the per-event running values are masked
O(B^2) segment reductions over the batch (one masked matmul / reduce).
"""

from __future__ import annotations

import jax.numpy as jnp

from siddhi_tpu.ops.prefix import extreme_identity, last_reset_index

# 64-bit mixing constants (splitmix64 finalizer) for combining composite keys.
_MIX1 = jnp.int64(-7046029254386353131)  # 0x9E3779B97F4A7C15 as signed
_MIX2 = jnp.int64(-4658895280553007687)  # 0xBF58476D1CE4E5B9 as signed


def mix_keys(cols: list[jnp.ndarray]) -> jnp.ndarray:
    """Combine one or more [B] integer-encoded key columns into one int64 key.

    Single-column keys pass through exactly (collision-free); composite keys are
    hash-mixed (the reference concatenates strings; a 64-bit mix keeps the
    device representation fixed-width — collisions are ~2^-64 per pair).
    """
    if len(cols) == 1:
        return cols[0].astype(jnp.int64)
    h = jnp.zeros_like(cols[0], dtype=jnp.int64)
    for c in cols:
        h = (h ^ c.astype(jnp.int64)) * _MIX1
        h = (h ^ (h >> 29)) * _MIX2
    return h


def assign_slots(
    table_keys: jnp.ndarray,  # [G] int64
    used: jnp.ndarray,        # [G] bool
    n_used: jnp.ndarray,      # scalar int32
    batch_keys: jnp.ndarray,  # [B] int64
    active: jnp.ndarray,      # [B] bool — rows that carry a group key
    reset: jnp.ndarray | None = None,  # [B] bool — RESET rows clear the table
):
    """Map each active row to a stable slot in [0, G); allocate new slots in
    first-appearance order. Inactive rows get slot == G (scatter-drop lane).

    RESET semantics: a reset kills every group's carried state, so rows after
    the batch's last reset re-allocate into a FRESH table (bounding table
    growth to per-bucket cardinality for batch windows — the reference's
    per-chunk group map has the same lifetime). Rows before the reset resolve
    against the old table, which only feeds the (pre-reset) carry gathers.

    Overflow: keys beyond capacity go to the dead lane G — their within-batch
    running values are still exact (computed from the `same` mask), but their
    carry is lost across batches; existing groups are never corrupted.

    Returns (new_table_keys, new_used, new_n_used, slot [B] int32,
    same [B, B] bool key-equality mask, overflow scalar bool).
    """
    g = table_keys.shape[0]
    b = batch_keys.shape[0]
    idx = jnp.arange(b, dtype=jnp.int32)

    same = (batch_keys[:, None] == batch_keys[None, :]) & active[:, None] & active[None, :]

    if reset is not None and reset.shape:
        marked = jnp.where(reset, idx, jnp.int32(-1))
        glr = jnp.max(marked)  # last reset row, -1 if none
    else:
        glr = jnp.int32(-1)
    any_reset = glr >= 0
    post = idx > glr  # rows whose carry lives in the (possibly fresh) new table

    # --- resolution against the old table (pre-reset gathers + no-reset case)
    eq_t = used[None, :] & (table_keys[None, :] == batch_keys[:, None])  # [B,G]
    in_t = eq_t.any(axis=1) & active
    t_slot = jnp.argmax(eq_t, axis=1).astype(jnp.int32)

    first = jnp.argmax(same, axis=1).astype(jnp.int32)  # first row with my key
    is_alloc = active & ~in_t & (first == idx)
    alloc_rank = (jnp.cumsum(is_alloc) - is_alloc).astype(jnp.int32)
    slot_new = n_used + alloc_rank
    old_overflow = (jnp.where(is_alloc, slot_new, 0) >= g).any()
    old_slot = jnp.where(in_t, t_slot, jnp.where(slot_new[first] < g, slot_new[first], g))
    old_slot = jnp.where(active, old_slot, jnp.int32(g)).astype(jnp.int32)

    # --- fresh-table resolution for post-reset rows
    post_active = active & post
    same_post = same & post[:, None] & post[None, :]
    first_post = jnp.argmax(same_post, axis=1).astype(jnp.int32)
    is_alloc_f = post_active & (first_post == idx)
    rank_f = (jnp.cumsum(is_alloc_f) - is_alloc_f).astype(jnp.int32)
    fresh_overflow = (jnp.where(is_alloc_f, rank_f, 0) >= g).any()
    fresh_slot = jnp.where(
        post_active & (rank_f[first_post] < g), rank_f[first_post], g
    ).astype(jnp.int32)

    slot = jnp.where(any_reset & post, fresh_slot, old_slot)
    slot = jnp.where(active, slot, jnp.int32(g))
    overflow = jnp.where(any_reset, fresh_overflow, old_overflow)

    # --- new table state
    # no reset: old table + this batch's allocations
    scatter_old = jnp.where(is_alloc & (slot_new < g) & ~any_reset, slot_new, g)
    keys_old = table_keys.at[scatter_old].set(batch_keys, mode="drop")
    used_old = used.at[scatter_old].set(True, mode="drop")
    n_old = jnp.minimum(n_used + is_alloc.sum(dtype=jnp.int32), g)
    # reset: fresh table from post-reset allocations only
    scatter_f = jnp.where(is_alloc_f & (rank_f < g) & any_reset, rank_f, g)
    keys_f = jnp.zeros_like(table_keys).at[scatter_f].set(batch_keys, mode="drop")
    used_f = jnp.zeros_like(used).at[scatter_f].set(True, mode="drop")
    n_f = jnp.minimum(is_alloc_f.sum(dtype=jnp.int32), g)

    new_keys = jnp.where(any_reset, keys_f, keys_old)
    new_used = jnp.where(any_reset, used_f, used_old)
    new_n = jnp.where(any_reset, n_f, n_old)
    return new_keys, new_used, new_n, slot, same, overflow


def _window_mask(same: jnp.ndarray, reset: jnp.ndarray) -> jnp.ndarray:
    """[B,B]: j contributes to i's running value — same key, j <= i, j after
    the last reset at or before i (RESET clears every group, matching the
    reference's batch-window reset of all group states)."""
    b = reset.shape[-1]
    idx = jnp.arange(b, dtype=jnp.int32)
    lr = last_reset_index(reset)
    return same & (idx[None, :] <= idx[:, None]) & (idx[None, :] > lr[:, None])


def keyed_running_sum(
    contrib: jnp.ndarray,  # [B], 0 on inactive rows
    same: jnp.ndarray,     # [B,B]
    reset: jnp.ndarray,    # [B]
    carry: jnp.ndarray,    # [G]
    slot: jnp.ndarray,     # [B] int32 (G = inactive)
):
    """Per-event running sum within each group; returns ([B] run, [G] carry')."""
    g = carry.shape[0]
    wm = _window_mask(same, reset)
    run = jnp.where(wm, contrib[None, :], 0).sum(axis=-1)
    lr = last_reset_index(reset)
    gathered = jnp.where(slot < g, carry[jnp.clip(slot, 0, g - 1)], 0)
    run = run + jnp.where(lr < 0, gathered, jnp.zeros_like(gathered))

    glr = lr[-1]
    post = jnp.arange(contrib.shape[0], dtype=jnp.int32) > glr
    base = jnp.where(reset.any(), jnp.zeros_like(carry), carry)
    new_carry = base.at[jnp.where(post, slot, g)].add(
        jnp.where(post, contrib, 0), mode="drop"
    )
    return run, new_carry


def keyed_running_extreme(
    values: jnp.ndarray,
    active: jnp.ndarray,
    same: jnp.ndarray,
    reset: jnp.ndarray,
    carry: jnp.ndarray,  # [G]
    slot: jnp.ndarray,
    is_min: bool,
):
    """Per-event running min/max within each group (no removal)."""
    g = carry.shape[0]
    ident = extreme_identity(values.dtype, is_min)
    wm = _window_mask(same, reset) & active[None, :]
    masked = jnp.where(wm, values[None, :], ident)
    red = masked.min(axis=-1) if is_min else masked.max(axis=-1)
    lr = last_reset_index(reset)
    gathered = jnp.where(
        (slot < g) & (lr < 0), carry[jnp.clip(slot, 0, g - 1)], ident
    )
    run = jnp.minimum(red, gathered) if is_min else jnp.maximum(red, gathered)

    post = jnp.arange(values.shape[0], dtype=jnp.int32) > lr[-1]
    base = jnp.where(reset.any(), jnp.full_like(carry, ident), carry)
    scatter = jnp.where(post & active, slot, g)
    vals_post = jnp.where(post & active, values, ident)
    if is_min:
        new_carry = base.at[scatter].min(vals_post, mode="drop")
    else:
        new_carry = base.at[scatter].max(vals_post, mode="drop")
    return run, new_carry
