"""Keyed (group-by) batch reductions and the device group-slot assignment.

The reference keeps one aggregator-state object per group key in a HashMap,
looked up per event by a generated string key
(reference: query/selector/GroupByKeyGenerator.java,
query/selector/attribute/processor/executor/GroupByAggregationAttributeExecutor.java).
TPU-shaped equivalent: group state is a fixed-capacity `[G]` array indexed by a
slot; slot assignment is a vectorized probe of a persistent int64 key table.
Within a batch, keyed running values ride a SORTED view of the rows — one
lexsort by (key, reset-era) turns every per-key reduction into a log-depth
segmented scan (ops/prefix.py), replacing the earlier [B,B] masked-reduction
formulation that allocated a 1G-element mask at B=32k.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.ops.prefix import (
    extreme_identity,
    last_reset_index,
    segmented_carry,
    segmented_cum_extreme,
    segmented_cumsum,
)
from siddhi_tpu.ops.scatter import compact_set_at, set_at

# 64-bit mixing constants (splitmix64 finalizer) for combining composite keys.
_MIX1 = np.int64(-7046029254386353131)  # 0x9E3779B97F4A7C15 as signed
_MIX2 = np.int64(-4658895280553007687)  # 0xBF58476D1CE4E5B9 as signed


def mix_keys(cols: list[jnp.ndarray]) -> jnp.ndarray:
    """Combine one or more [B] integer-encoded key columns into one int64 key.

    Single-column keys pass through exactly (collision-free); composite keys are
    hash-mixed (the reference concatenates strings; a 64-bit mix keeps the
    device representation fixed-width — collisions are ~2^-64 per pair).
    """
    if len(cols) == 1:
        return cols[0].astype(jnp.int64)
    h = jnp.zeros_like(cols[0], dtype=jnp.int64)
    for c in cols:
        h = (h ^ c.astype(jnp.int64)) * _MIX1
        h = (h ^ (h >> 29)) * _MIX2
    return h


def permute_by(key: jnp.ndarray, *lanes: jnp.ndarray) -> tuple:
    """Apply the permutation that sorts `key` ascending to every lane with ONE
    multi-operand bitonic sort. XLA:TPU runs sorts on the vector units
    (~1 ns/element) but gathers/scatters on the scalar core (~6.5 ns/element),
    so `x[perm]` for a known permutation is ~6x cheaper as a payload sort.
    `key` must be a permutation-ranking (all distinct); lanes ride along."""
    res = jax.lax.sort((key, *lanes), num_keys=1, is_stable=False)
    return res[1:]


@dataclasses.dataclass
class SortedGroups:
    """Sorted per-batch view: rows permuted by (active, reset-era, key, idx).

    perm:      [B] int32 — sorted position -> original row
    inv:       [B] int32 — original row -> sorted position
    seg_start: [B] bool  — sorted position begins a (era, key) segment
    """

    perm: jnp.ndarray
    inv: jnp.ndarray
    seg_start: jnp.ndarray

    def to_sorted(self, *lanes):
        """lanes[i][perm] for every lane — one payload sort, no gathers."""
        return permute_by(self.inv, *lanes)

    def from_sorted(self, *lanes):
        """lanes[i][inv] (undo to_sorted) — one payload sort, no gathers."""
        return permute_by(self.perm, *lanes)


def assign_slots(
    table_keys: jnp.ndarray,  # [G] int64
    used: jnp.ndarray,        # [G] bool
    n_used: jnp.ndarray,      # scalar int32
    batch_keys: jnp.ndarray,  # [B] int64
    active: jnp.ndarray,      # [B] bool — rows that carry a group key
    reset: jnp.ndarray | None = None,  # [B] bool — RESET rows clear the table
):
    """Map each active row to a stable slot in [0, G); allocate new slots in
    first-appearance order. Inactive rows get slot == G (scatter-drop lane).

    RESET semantics: a reset kills every group's carried state, so rows after
    the batch's last reset re-allocate into a FRESH table (bounding table
    growth to per-bucket cardinality for batch windows — the reference's
    per-chunk group map has the same lifetime). Rows before the reset resolve
    against the old table, which only feeds the (pre-reset) carry gathers.

    Overflow: keys beyond capacity go to the dead lane G — their within-batch
    running values are still exact (computed over the sorted segments), but
    their carry is lost across batches; existing groups are never corrupted.

    Returns (new_table_keys, new_used, new_n_used, slot [B] int32,
    SortedGroups, overflow scalar bool).
    """
    g = table_keys.shape[0]
    b = batch_keys.shape[0]
    idx = jnp.arange(b, dtype=jnp.int32)

    has_reset = reset is not None and getattr(reset, "shape", None)
    rst = reset if has_reset else jnp.zeros((b,), jnp.bool_)
    glr = jnp.max(jnp.where(rst, idx, np.int32(-1)))  # last reset row, -1 if none
    any_reset = glr >= 0
    post = idx > glr  # rows whose carry lives in the (possibly fresh) new table
    era = jnp.cumsum(rst.astype(jnp.int32))  # segments never span a reset

    # ---- sorted view: actives first, grouped by (era, key), stable by idx.
    # ONE multi-key payload sort replaces lexsort + per-lane [perm] gathers
    # (sorts ride the vector units; gathers serialize on the scalar core),
    # and the inverse permutation comes from a second payload sort instead
    # of a [B]-update scatter.
    inact = (~active).astype(jnp.int32)
    inact_s, se, sk, perm, sa = jax.lax.sort(
        (inact, era, batch_keys, idx, active), num_keys=4, is_stable=False
    )
    del inact_s
    seg_start = jnp.concatenate(
        [
            jnp.ones((1,), jnp.bool_),
            (sk[1:] != sk[:-1]) | (se[1:] != se[:-1]) | (sa[1:] != sa[:-1]),
        ]
    )
    (inv,) = permute_by(perm, idx)
    grp = SortedGroups(perm=perm, inv=inv, seg_start=seg_start)

    # first row (original index) holding each row's (era, key) — via the
    # segment head carried across its segment, inverse-permuted
    (first,) = grp.from_sorted(segmented_carry(perm, seg_start))

    # ---- resolution against the old table (pre-reset gathers + no-reset case)
    # dense [B, G] eq matrix: at G <= ~1k this is a fully vectorized compare +
    # argmax the VPU eats (~0.5 ms at B=100k) — measured FASTER than a
    # searchsorted probe, whose log G binary-search steps serialize into
    # scalar-space gathers on TPU
    eq_t = used[None, :] & (table_keys[None, :] == batch_keys[:, None])  # [B,G]
    in_t = eq_t.any(axis=1) & active
    t_slot = jnp.argmax(eq_t, axis=1).astype(jnp.int32)

    is_alloc = active & ~in_t & (first == idx)
    alloc_rank = (jnp.cumsum(is_alloc.astype(jnp.int32)) - is_alloc).astype(jnp.int32)
    slot_new = n_used + alloc_rank
    old_overflow = (jnp.where(is_alloc, slot_new, 0) >= g).any()
    old_slot = jnp.where(in_t, t_slot, jnp.where(slot_new[first] < g, slot_new[first], g))
    old_slot = jnp.where(active, old_slot, np.int32(g)).astype(jnp.int32)

    # ---- fresh-table resolution for post-reset rows (first is era-local, so
    # the same head works for the fresh allocation pass)
    post_active = active & post
    is_alloc_f = post_active & (first == idx)
    rank_f = (jnp.cumsum(is_alloc_f.astype(jnp.int32)) - is_alloc_f).astype(jnp.int32)
    fresh_overflow = (jnp.where(is_alloc_f, rank_f, 0) >= g).any()
    fresh_slot = jnp.where(
        post_active & (rank_f[first] < g), rank_f[first], g
    ).astype(jnp.int32)

    slot = jnp.where(any_reset & post, fresh_slot, old_slot)
    slot = jnp.where(active, slot, np.int32(g))
    overflow = jnp.where(any_reset, fresh_overflow, old_overflow)

    # ---- new table state (compact_set_at: sort the <=G live writers to the
    # front so the scatter touches G updates, not B — and int64 key scatters
    # ride the int32-pair path either way, ops/scatter.py)
    ones_b = jnp.ones((b,), jnp.bool_)
    # no reset: old table + this batch's allocations
    scatter_old = jnp.where(is_alloc & (slot_new < g) & ~any_reset, slot_new, g)
    keys_old = compact_set_at(table_keys, scatter_old, batch_keys)
    used_old = compact_set_at(used, scatter_old, ones_b)
    n_old = jnp.minimum(n_used + is_alloc.sum(dtype=jnp.int32), g)
    # reset: fresh table from post-reset allocations only
    scatter_f = jnp.where(is_alloc_f & (rank_f < g) & any_reset, rank_f, g)
    keys_f = compact_set_at(jnp.zeros_like(table_keys), scatter_f, batch_keys)
    used_f = compact_set_at(jnp.zeros_like(used), scatter_f, ones_b)
    n_f = jnp.minimum(is_alloc_f.sum(dtype=jnp.int32), g)

    new_keys = jnp.where(any_reset, keys_f, keys_old)
    new_used = jnp.where(any_reset, used_f, used_old)
    new_n = jnp.where(any_reset, n_f, n_old)
    return new_keys, new_used, new_n, slot, grp, overflow


def _final_segment_writers(grp: SortedGroups, slot, post):
    """Sorted-space mask of rows that END a final-era (post-last-reset)
    segment, with their slots — the one row per live group whose running
    value IS the group's new carry. Lets 64-bit carries update via a
    scatter-SET (int32-pair fast path) instead of a serialized 64-bit
    scatter reduction."""
    seg_end = jnp.concatenate([grp.seg_start[1:], jnp.ones((1,), jnp.bool_)])
    slot_s, post_s = grp.to_sorted(slot, post)
    return seg_end & post_s, slot_s


def keyed_running_sum(
    contrib: jnp.ndarray,  # [B], 0 on inactive rows
    grp: SortedGroups,
    reset: jnp.ndarray,    # [B]
    carry: jnp.ndarray,    # [G]
    slot: jnp.ndarray,     # [B] int32 (G = inactive)
):
    """Per-event running sum within each group; returns ([B] run, [G] carry').

    The (era, key) segmentation bounds contributions to same-key rows j <= i
    with no reset in between — exactly the reference's per-key running state
    with RESET zeroing every group."""
    g = carry.shape[0]
    (contrib_s,) = grp.to_sorted(contrib)
    run_s = segmented_cumsum(contrib_s, grp.seg_start)
    (run,) = grp.from_sorted(run_s)
    lr = last_reset_index(reset)
    gathered = jnp.where(slot < g, carry[jnp.clip(slot, 0, g - 1)], 0)
    run = run + jnp.where(lr < 0, gathered, jnp.zeros_like(gathered))

    glr = lr[-1]
    post = jnp.arange(contrib.shape[0], dtype=jnp.int32) > glr
    base = jnp.where(reset.any(), jnp.zeros_like(carry), carry)
    # in the final era each live group is exactly one sorted segment, so its
    # carry is base + the segment END's running sum — one unique writer per
    # group, compacted so the scatter costs G updates (B-update scatters and
    # 64-bit scatter reductions both serialize on the TPU scalar core)
    writer, slot_s = _final_segment_writers(grp, slot, post)
    writer = writer & (slot_s < g)
    newval = (
        jnp.where(slot_s < g, base[jnp.clip(slot_s, 0, g - 1)], 0) + run_s
    ).astype(carry.dtype)
    new_carry = compact_set_at(base, jnp.where(writer, slot_s, g), newval)
    return run, new_carry


def keyed_running_extreme(
    values: jnp.ndarray,
    active: jnp.ndarray,
    grp: SortedGroups,
    reset: jnp.ndarray,
    carry: jnp.ndarray,  # [G]
    slot: jnp.ndarray,
    is_min: bool,
):
    """Per-event running min/max within each group (no removal)."""
    g = carry.shape[0]
    ident = extreme_identity(values.dtype, is_min)
    op = jnp.minimum if is_min else jnp.maximum
    masked = jnp.where(active, values, ident)
    (masked_s,) = grp.to_sorted(masked)
    run_s = segmented_cum_extreme(masked_s, grp.seg_start, is_min)
    (run,) = grp.from_sorted(run_s)
    lr = last_reset_index(reset)
    gathered = jnp.where(
        (slot < g) & (lr < 0), carry[jnp.clip(slot, 0, g - 1)], ident
    )
    run = op(run, gathered)

    post = jnp.arange(values.shape[0], dtype=jnp.int32) > lr[-1]
    base = jnp.where(reset.any(), jnp.full_like(carry, ident), carry)
    # one unique writer per live group (its final-era segment end), compacted
    # — see keyed_running_sum
    writer, slot_s = _final_segment_writers(grp, slot, post)
    writer = writer & (slot_s < g)
    newval = op(
        jnp.where(slot_s < g, base[jnp.clip(slot_s, 0, g - 1)], ident),
        run_s,
    ).astype(carry.dtype)
    new_carry = compact_set_at(base, jnp.where(writer, slot_s, g), newval)
    return run, new_carry


def keep_last_in_sorted(
    grp: SortedGroups, kind: jnp.ndarray, valid: jnp.ndarray
) -> jnp.ndarray:
    """[B] bool: valid rows that are the LAST valid row of their
    (segment, kind) — the batch-mode group-by collapse, computed inside an
    EXISTING SortedGroups view instead of re-lexsorting (the segments of
    `grp` are exactly the (reset-era, key) groups; `kind` subdivides them).
    One reverse segmented max per kind lane, no new sort.

    Precondition: `valid` is pre-masked to CURRENT|EXPIRED rows — other kinds
    would silently compete in the EXPIRED lane."""
    from siddhi_tpu.core.event import KIND_CURRENT, KIND_EXPIRED

    b = valid.shape[0]
    idx = jnp.arange(b, dtype=jnp.int32)
    sv, sk = grp.to_sorted(valid, kind.astype(jnp.int32))
    seg_end = jnp.concatenate([grp.seg_start[1:], jnp.ones((1,), jnp.bool_)])
    rev_start = seg_end[::-1]

    def last_of(kbit):
        marked = jnp.where(sv & (sk == kbit), grp.perm, np.int32(-1))
        return segmented_cum_extreme(marked[::-1], rev_start, is_min=False)[::-1]

    last_cur = last_of(int(KIND_CURRENT))
    last_exp = last_of(int(KIND_EXPIRED))
    last_for_row = jnp.where(sk == int(KIND_CURRENT), last_cur, last_exp)
    (lfr,) = grp.from_sorted(last_for_row)
    return valid & (lfr == idx)


def keep_last_per_group(cols: list[jnp.ndarray], valid: jnp.ndarray) -> jnp.ndarray:
    """[B] bool: valid rows that are the LAST valid row of their group, where a
    group is the tuple of `cols` values (reference: QuerySelector
    processInBatchGroupBy — the map keeps one entry per key, last write wins).
    O(B log B): sort by group, find each group's last valid row index."""
    b = valid.shape[0]
    idx = jnp.arange(b, dtype=jnp.int32)
    # one payload sort: cols as keys (idx last for a total order), valid rides
    sorted_ops = jax.lax.sort(
        (*cols, idx, valid), num_keys=len(cols) + 1, is_stable=False
    )
    scols, perm, sv = sorted_ops[: len(cols)], sorted_ops[-2], sorted_ops[-1]
    boundary = jnp.zeros((b,), jnp.bool_).at[0].set(True)
    for c in scols:
        boundary = boundary | jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), c[1:] != c[:-1]]
        )
    # last valid original-row index per segment: reverse segmented cummax of
    # where(valid, original row, -1)
    marked = jnp.where(sv, perm, np.int32(-1))
    rev = marked[::-1]
    # a reversed segment starts where the forward segment ENDS
    seg_end = jnp.concatenate([boundary[1:], jnp.ones((1,), jnp.bool_)])
    rev_start = seg_end[::-1]
    last_in_seg = segmented_cum_extreme(rev, rev_start, is_min=False)[::-1]
    (last_back,) = permute_by(perm, last_in_seg)
    return valid & (last_back == idx)
