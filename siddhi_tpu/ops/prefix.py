"""Reset-aware running (prefix) reductions over a batch.

The reference updates aggregator state one event at a time, emitting the running
value after each event and zeroing state on RESET events
(reference: query/selector/attribute/aggregator/*.java — add/remove on
CURRENT/EXPIRED, reset on RESET). Batched on TPU, the per-event running values
become prefix reductions with reset barriers. For the (small, padded) batch axis
we use an O(B^2) lower-triangular mask formulation: it is one matmul / masked
reduction, which the MXU/VPU eat for B <= ~1024, and it keeps everything static.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def last_reset_index(reset: jnp.ndarray) -> jnp.ndarray:
    """For each position i, the largest j <= i with reset[j], else -1. [B] int32."""
    import jax.lax as lax

    idx = jnp.arange(reset.shape[-1], dtype=jnp.int32)
    marked = jnp.where(reset, idx, np.int32(-1))
    # lax.cummax is a parallel (log-depth) scan; jnp.maximum.accumulate
    # lowers to a sequential per-element scan — ~1000x slower at 100k rows
    return lax.cummax(marked, axis=reset.ndim - 1)


def window_mask(reset: jnp.ndarray) -> jnp.ndarray:
    """[B, B] bool: M[i, j] True iff event j contributes to the running value at
    i — j <= i and j strictly after the last reset at or before i."""
    idx = jnp.arange(reset.shape[-1], dtype=jnp.int32)
    lr = last_reset_index(reset)
    return (idx[None, :] <= idx[:, None]) & (idx[None, :] > lr[:, None])


def running_sum(
    contrib: jnp.ndarray, reset: jnp.ndarray, base: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Running sum after each event with reset barriers — O(B) via cumsum:
    run_i = csum_i - csum[last_reset_i] (+ carry before the first reset).

    contrib: [B] signed contributions (0 for invalid/timer/reset rows)
    reset:   [B] bool reset-event marks
    base:    scalar carried sum from prior batches
    returns: ([B] running values, scalar new carry)
    """
    csum = jnp.cumsum(contrib)
    lr = last_reset_index(reset)
    at_lr = jnp.where(lr >= 0, csum[jnp.clip(lr, 0)], jnp.zeros_like(csum[0]))
    run = csum - at_lr
    no_reset_yet = lr < 0
    run = run + jnp.where(no_reset_yet, base, jnp.zeros_like(base))
    return run, run[-1]


def running_extreme(
    values: jnp.ndarray,
    active: jnp.ndarray,
    reset: jnp.ndarray,
    base: jnp.ndarray,
    is_min: bool,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Running min/max (no removal — forever semantics / non-windowed), O(B)
    via a segmented associative scan (reset starts a new segment).

    values: [B]; active: [B] bool (valid CURRENT rows); base: scalar carry
    (identity = +/-inf or int extreme when nothing seen yet).
    """
    import jax.lax as lax

    ident = extreme_identity(values.dtype, is_min)
    op = jnp.minimum if is_min else jnp.maximum
    masked = jnp.where(active, values, ident)

    def combine(a, b):
        av, ar = a
        bv, br = b
        return jnp.where(br, bv, op(av, bv)), ar | br

    red, _ = lax.associative_scan(combine, (masked, reset))
    base_eff = jnp.where(last_reset_index(reset) < 0, base, ident)
    run = op(red, base_eff)
    return run, run[-1]


def _segmented_scan(vals: jnp.ndarray, seg_start: jnp.ndarray, op) -> jnp.ndarray:
    """Inclusive segment-wise scan: positions with seg_start restart the
    accumulator. Log-depth associative scan — the O(B) replacement for the
    [B,B] masked-reduction form of keyed running values."""
    import jax.lax as lax

    def combine(a, b):
        av, ar = a
        bv, br = b
        return jnp.where(br, bv, op(av, bv)), ar | br

    out, _ = lax.associative_scan(combine, (vals, seg_start))
    return out


def segmented_cumsum(vals: jnp.ndarray, seg_start: jnp.ndarray) -> jnp.ndarray:
    """Inclusive segment-wise running sum."""
    return _segmented_scan(vals, seg_start, lambda a, b: a + b)


def segmented_cum_extreme(
    vals: jnp.ndarray, seg_start: jnp.ndarray, is_min: bool
) -> jnp.ndarray:
    """Inclusive segment-wise running min/max."""
    return _segmented_scan(
        vals, seg_start, jnp.minimum if is_min else jnp.maximum
    )


def segmented_carry(vals: jnp.ndarray, seg_start: jnp.ndarray) -> jnp.ndarray:
    """Propagate each segment's first value across the segment."""
    return _segmented_scan(vals, seg_start, lambda a, b: a)


def extreme_identity(dtype, is_min: bool) -> np.ndarray:
    # numpy (NOT jnp): this is called at trace time and the result is baked
    # into compiled programs; a concrete jax.Array const knocks PJRT dispatch
    # off its fast path process-wide on tunneled backends.
    if jnp.issubdtype(dtype, jnp.floating):
        return np.asarray(np.inf if is_min else -np.inf, dtype=dtype)
    info = jnp.iinfo(dtype)
    return np.asarray(info.max if is_min else info.min, dtype=dtype)


def compact(valid: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Stable-compaction permutation: indices that move valid rows to the front.

    returns (perm [B] int32, count scalar int32). Gather with `perm` then mask
    rows >= count.
    """
    perm = jnp.argsort(~valid, stable=True).astype(jnp.int32)
    count = valid.sum(dtype=jnp.int32)
    return perm, count
