"""Reset-aware running (prefix) reductions over a batch.

The reference updates aggregator state one event at a time, emitting the running
value after each event and zeroing state on RESET events
(reference: query/selector/attribute/aggregator/*.java — add/remove on
CURRENT/EXPIRED, reset on RESET). Batched on TPU, the per-event running values
become prefix reductions with reset barriers. For the (small, padded) batch axis
we use an O(B^2) lower-triangular mask formulation: it is one matmul / masked
reduction, which the MXU/VPU eat for B <= ~1024, and it keeps everything static.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def cummax(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive running maximum over a [B] axis (blocked full-width scan)."""
    (out,) = _blocked_scan((x,), lambda a, b: (jnp.maximum(a[0], b[0]),))
    return out


def cumsum(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive running sum. 32-bit inputs use the native lowering; 64-bit
    inputs use the blocked scan — XLA:TPU lowers cumsum to a reduce-window
    whose int64 (u32-pair) variadic form blows the scoped-vmem budget inside
    larger programs (observed 'Ran out of memory in memory space vmem ...
    reduce-window (u32[2,128], u32[2,128])' AOT failures)."""
    if x.dtype.itemsize >= 8:
        (out,) = _blocked_scan((x,), lambda a, b: (a[0] + b[0],))
        return out
    return jnp.cumsum(x)


def last_reset_index(reset: jnp.ndarray) -> jnp.ndarray:
    """For each position i, the largest j <= i with reset[j], else -1. [B] int32."""
    idx = jnp.arange(reset.shape[-1], dtype=jnp.int32)
    marked = jnp.where(reset, idx, np.int32(-1))
    return cummax(marked)


def window_mask(reset: jnp.ndarray) -> jnp.ndarray:
    """[B, B] bool: M[i, j] True iff event j contributes to the running value at
    i — j <= i and j strictly after the last reset at or before i."""
    idx = jnp.arange(reset.shape[-1], dtype=jnp.int32)
    lr = last_reset_index(reset)
    return (idx[None, :] <= idx[:, None]) & (idx[None, :] > lr[:, None])


def running_sum(
    contrib: jnp.ndarray, reset: jnp.ndarray, base: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Running sum after each event with reset barriers — O(B) via cumsum:
    run_i = csum_i - csum[last_reset_i] (+ carry before the first reset).

    contrib: [B] signed contributions (0 for invalid/timer/reset rows)
    reset:   [B] bool reset-event marks
    base:    scalar carried sum from prior batches
    returns: ([B] running values, scalar new carry)
    """
    csum = cumsum(contrib)
    lr = last_reset_index(reset)
    at_lr = jnp.where(lr >= 0, csum[jnp.clip(lr, 0)], jnp.zeros_like(csum[0]))
    run = csum - at_lr
    no_reset_yet = lr < 0
    run = run + jnp.where(no_reset_yet, base, jnp.zeros_like(base))
    return run, run[-1]


def running_extreme(
    values: jnp.ndarray,
    active: jnp.ndarray,
    reset: jnp.ndarray,
    base: jnp.ndarray,
    is_min: bool,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Running min/max (no removal — forever semantics / non-windowed), O(B)
    via a segmented associative scan (reset starts a new segment).

    values: [B]; active: [B] bool (valid CURRENT rows); base: scalar carry
    (identity = +/-inf or int extreme when nothing seen yet).
    """
    ident = extreme_identity(values.dtype, is_min)
    op = jnp.minimum if is_min else jnp.maximum
    masked = jnp.where(active, values, ident)

    def combine(a, b):
        av, ar = a
        bv, br = b
        return jnp.where(br, bv, op(av, bv)), ar | br

    red, _ = _blocked_scan((masked, reset), combine)
    base_eff = jnp.where(last_reset_index(reset) < 0, base, ident)
    run = op(red, base_eff)
    return run, run[-1]


_SCAN_LANES = 512


def _hillis_steele(mats: tuple, combine, width: int, axis_len: int):
    """Inclusive scan along the last axis via Hillis-Steele doubling: every
    level is a full-width vectorized shift+combine (pad/slice + select), so
    nothing lands in TPU scalar space. O(n log n) work, log n levels."""
    lane = jnp.arange(width, dtype=jnp.int32)
    cur = mats
    d = 1
    while d < axis_len:
        shifted = tuple(
            jnp.pad(m, [(0, 0)] * (m.ndim - 1) + [(d, 0)])[..., :width]
            for m in cur
        )
        comb = combine(shifted, cur)
        cur = tuple(
            jnp.where(lane >= d, cm, c) for cm, c in zip(comb, cur)
        )
        d *= 2
    return cur


def _blocked_scan(elems: tuple, combine) -> tuple:
    """Inclusive scan of tuple-valued elements over a [B] axis, shaped for
    TPU: scan lanes of a [B/L, L] view in parallel, scan the per-block
    totals, then fold each block's prefix back in. `lax.associative_scan`'s
    recursive halving creates dozens of tiny odd-shaped kernels that execute
    from scalar memory and dominate whole-query step time (profiled at ~85%
    of a group-by step at B=32k); this formulation is 3 passes of full-width
    vector work."""
    b = elems[0].shape[0]
    L = _SCAN_LANES
    if b % L != 0 and b > 2 * L:
        # pad to a lane multiple: an INCLUSIVE forward scan's first b outputs
        # never depend on tail padding, so zero-fill + slice-back is exact.
        # Without this, any off-multiple flow length silently falls into
        # lax.associative_scan's recursive halving (~13x slower, measured).
        pad = (-b) % L
        padded = tuple(jnp.pad(e, (0, pad)) for e in elems)
        out = _blocked_scan(padded, combine)
        return tuple(o[:b] for o in out)
    if b % L != 0 or b // L < 2:
        import jax.lax as lax

        return lax.associative_scan(lambda a, c: combine(a, c), elems)
    # PRED tensors (sub-byte (4,1) tiling) push these fusions onto the TPU
    # scalar path — 13x slower measured at B=32k. Carry flags as int32
    # between levels; the user combine still sees bools.
    was_bool = tuple(e.dtype == jnp.bool_ for e in elems)

    def wrapped(a, c):
        ab = tuple(x.astype(bool) if wb else x for x, wb in zip(a, was_bool))
        cb = tuple(x.astype(bool) if wb else x for x, wb in zip(c, was_bool))
        out = combine(ab, cb)
        return tuple(
            x.astype(jnp.int32) if wb else x for x, wb in zip(out, was_bool)
        )

    elems = tuple(
        e.astype(jnp.int32) if wb else e for e, wb in zip(elems, was_bool)
    )
    n = b // L
    mats = tuple(e.reshape(n, L) for e in elems)
    scanned = _hillis_steele(mats, wrapped, L, L)
    # block totals -> exclusive block prefixes (scan the [N] totals)
    totals = tuple(m[:, -1] for m in scanned)
    tot_scan = _hillis_steele(totals, wrapped, n, n)
    prev = tuple(jnp.pad(t, (1, 0))[:-1] for t in tot_scan)
    has_prev = jnp.arange(n, dtype=jnp.int32) > 0
    folded = wrapped(tuple(p[:, None] for p in prev), scanned)
    out = tuple(
        jnp.where(has_prev[:, None], f, s).reshape(b)
        for f, s in zip(folded, scanned)
    )
    return tuple(
        o.astype(bool) if wb else o for o, wb in zip(out, was_bool)
    )


def _segmented_scan(vals: jnp.ndarray, seg_start: jnp.ndarray, op) -> jnp.ndarray:
    """Inclusive segment-wise scan: positions with seg_start restart the
    accumulator. Blocked full-width scan — the O(B log B) replacement for the
    [B,B] masked-reduction form of keyed running values."""

    def combine(a, b):
        av, ar = a
        bv, br = b
        return jnp.where(br, bv, op(av, bv)), ar | br

    out, _ = _blocked_scan((vals, seg_start), combine)
    return out


def segmented_cumsum(vals: jnp.ndarray, seg_start: jnp.ndarray) -> jnp.ndarray:
    """Inclusive segment-wise running sum."""
    return _segmented_scan(vals, seg_start, lambda a, b: a + b)


def segmented_cum_extreme(
    vals: jnp.ndarray, seg_start: jnp.ndarray, is_min: bool
) -> jnp.ndarray:
    """Inclusive segment-wise running min/max."""
    return _segmented_scan(
        vals, seg_start, jnp.minimum if is_min else jnp.maximum
    )


def segmented_carry(vals: jnp.ndarray, seg_start: jnp.ndarray) -> jnp.ndarray:
    """Propagate each segment's first value across the segment."""
    return _segmented_scan(vals, seg_start, lambda a, b: a)


def extreme_identity(dtype, is_min: bool) -> np.ndarray:
    # numpy (NOT jnp): this is called at trace time and the result is baked
    # into compiled programs; a concrete jax.Array const knocks PJRT dispatch
    # off its fast path process-wide on tunneled backends.
    if jnp.issubdtype(dtype, jnp.floating):
        return np.asarray(np.inf if is_min else -np.inf, dtype=dtype)
    info = jnp.iinfo(dtype)
    return np.asarray(info.max if is_min else info.min, dtype=dtype)


def first_indices(mask: jnp.ndarray, size: int, fill: int = -1) -> jnp.ndarray:
    """Indices of the first `size` True positions, int32 — the engine's
    replacement for `jnp.nonzero(mask, size=, fill_value=)[0]`, whose internal
    cumsum is int64 under x64 and lowers to the vmem-hungry u32-pair
    reduce-window on XLA:TPU (observed AOT OOM inside fused programs)."""
    n = mask.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    rank = jnp.cumsum(mask.astype(jnp.int32)) - 1
    dst = jnp.where(mask & (rank < size), rank, size)
    return (
        jnp.full((size,), fill, jnp.int32).at[dst].set(idx, mode="drop")
    )


def compact(valid: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Stable-compaction permutation: indices that move valid rows to the front.

    returns (perm [B] int32, count scalar int32). Gather with `perm` then mask
    rows >= count.
    """
    perm = jnp.argsort(~valid, stable=True).astype(jnp.int32)
    count = valid.sum(dtype=jnp.int32)
    return perm, count
