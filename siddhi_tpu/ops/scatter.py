"""TPU-shaped scatter helpers.

XLA:TPU lowers scatters of 64-bit values (int64 under x64, float64) to a
serialized scalar-space loop — measured 5-11 ms for a [100k] -> [1k]
scatter-set where the same scatter of int32/float32 values is sub-millisecond.
The fix is mechanical: split 64-bit lanes into hi/lo int32 halves (arithmetic
shift/mask, NOT bitcast-convert — chaining bitcasts with the wire codec's
u8 decode trips an XLA simplifier verifier bug), scatter the halves on the
32-bit fast path, recombine. Semantics are identical for `set` (whole-value
replacement); 64-bit reductions (add/min/max) cannot ride the split and
should be reformulated (sort + searchsorted) instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _is_wide(dtype) -> bool:
    return jnp.dtype(dtype).itemsize >= 8


def _split64(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    xi = (
        x
        if jnp.issubdtype(x.dtype, jnp.integer)
        else jax.lax.bitcast_convert_type(x, jnp.int64)
    )
    lo = (xi & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (xi >> jnp.int64(32)).astype(jnp.int32)
    return lo, hi


def _join64(lo: jnp.ndarray, hi: jnp.ndarray, dtype) -> jnp.ndarray:
    xi = (hi.astype(jnp.int64) << jnp.int64(32)) | lo.astype(jnp.int64)
    if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        return xi.astype(dtype)
    return jax.lax.bitcast_convert_type(xi, dtype)


def set_at(dst: jnp.ndarray, idx: jnp.ndarray, src: jnp.ndarray, *, mode: str = "drop") -> jnp.ndarray:
    """`dst.at[idx].set(src, mode=...)` that stays off the TPU scalar path for
    64-bit dtypes (first-axis index scatter)."""
    if not _is_wide(dst.dtype):
        return dst.at[idx].set(src.astype(dst.dtype), mode=mode)
    dlo, dhi = _split64(dst)
    slo, shi = _split64(src.astype(dst.dtype))
    return _join64(
        dlo.at[idx].set(slo, mode=mode),
        dhi.at[idx].set(shi, mode=mode),
        dst.dtype,
    )


def compact_set_at(
    dst: jnp.ndarray, idx: jnp.ndarray, src: jnp.ndarray
) -> jnp.ndarray:
    """Scatter-set with a LARGE sparse index vector into a SMALL target:
    `dst[G].at[idx[B]].set(src[B])` where at most one live writer exists per
    slot and dead lanes carry idx >= G (any out-of-range index is dead, not
    just the == G sentinel).

    XLA:TPU executes scatter at ~one UPDATE per scalar-core step, so a [B]
    index vector costs ~B regardless of how few writers are live. One
    multi-operand bitonic sort (~1 ns/element, vectorized) moves the live
    writers to the front, and the real scatter then touches only [G] updates.
    Net: B-update scatter -> sort(B) + G-update scatter, ~4-6x faster for
    B >> G. Falls back to the plain scatter when B <= G."""
    g = dst.shape[0]
    b = idx.shape[0]
    if b <= g:
        return set_at(dst, idx, src)
    key = jnp.where(idx < g, idx, b).astype(jnp.int32)  # dead lanes sort last
    key_s, src_s = jax.lax.sort(
        (key, src), num_keys=1, is_stable=False
    )
    return set_at(dst, jnp.where(key_s[:g] < g, key_s[:g], g), src_s[:g])


